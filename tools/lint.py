#!/usr/bin/env python3
"""First-party Python lint gate (the jsstyle/javascriptlint analog).

The reference gates CI on vendored linters (`make check` runs jsstyle +
javascriptlint, reference Jenkinsfile:37-40, deps/jsstyle,
deps/javascriptlint); this image ships no Python linter, so this tool
implements the high-signal, zero-false-positive subset used by `make
check`.  Zero findings is the passing state; every rule here is cheap to
satisfy and each finding is a real smell:

  unused-import        imported name never referenced in the module
  import-shadowed      def/class rebinds an imported name
  bare-except          `except:` catches SystemExit/KeyboardInterrupt
  duplicate-dict-key   constant key repeated in a dict literal
  f-string-no-placeholder  f-prefix on a string with no {…}
  is-literal           `is` / `is not` against a str/number literal
  mutable-default      def f(x=[]) / f(x={}) / f(x=set())
  assert-tuple         assert (cond, "msg") — always true

Usage: python tools/lint.py <paths...>   (directories are walked for .py
files; explicit files are linted regardless of extension so bin/ scripts
can be covered).
"""
import ast
import os
import re
import sys


class Finding:
    def __init__(self, path, line, rule, msg):
        self.path = path
        self.line = line
        self.rule = rule
        self.msg = msg

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


def iter_strings(node):
    """All string constants syntactically inside `node` (docstrings and
    __all__ entries count as usage for re-export barrels)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


class Linter(ast.NodeVisitor):
    def __init__(self, path, tree, source):
        self.path = path
        self.tree = tree
        self.source = source
        self.findings = []

    def add(self, node, rule, msg):
        self.findings.append(Finding(self.path, node.lineno, rule, msg))

    def run(self):
        self.check_imports()
        self.visit(self.tree)
        return self.findings

    # ---- unused imports / shadowing (module scope) ----

    def check_imports(self):
        # __init__.py imports are re-export surface (the lib/index.js
        # barrel pattern); "unused" is their whole point
        barrel = os.path.basename(self.path) == "__init__.py"
        imported = {}   # name -> (node, reported_name)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    imported.setdefault(name, (node, a.asname or a.name))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    imported.setdefault(name, (node, name))

        used = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                # handled via the Name at the base of the chain
                pass
        # names mentioned in strings count (docstring references, __all__,
        # typing forward refs)
        strings = set()
        for s in iter_strings(self.tree):
            if len(s) < 200:
                for tok in s.replace(",", " ").replace("'", " ").split():
                    strings.add(tok.strip("\"`()[]{}.:;"))

        redefined = set()
        # module-level defs only: a method or nested function named like
        # an import does not rebind the module-level name
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                if node.name in imported:
                    redefined.add(node.name)
                    self.add(node, "import-shadowed",
                             f"definition of {node.name!r} shadows an "
                             f"import of the same name")

        if barrel:
            return
        for name, (node, reported) in imported.items():
            if name.startswith("_") or name in redefined:
                continue
            if name not in used and name not in strings:
                self.add(node, "unused-import",
                         f"{reported!r} imported but unused")

    # ---- node-local rules ----

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self.add(node, "bare-except",
                     "bare `except:` also catches SystemExit/"
                     "KeyboardInterrupt; use `except Exception:`")
        self.generic_visit(node)

    def visit_Dict(self, node):
        seen = {}
        for k in node.keys:
            if isinstance(k, ast.Constant):
                try:
                    hash(k.value)
                except TypeError:
                    continue
                if k.value in seen:
                    self.add(k, "duplicate-dict-key",
                             f"duplicate dict key {k.value!r}")
                seen[k.value] = True
        self.generic_visit(node)

    def visit_JoinedStr(self, node):
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.add(node, "f-string-no-placeholder",
                     "f-string has no placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node):
        # format specs (f"{x:>3}") are themselves JoinedStr nodes holding
        # only Constants; don't descend or every spec is a false positive
        self.visit(node.value)

    def visit_Compare(self, node):
        # chained comparisons: op[i] compares comparators[i-1] (or .left
        # for i == 0) with comparators[i]
        lefts = [node.left] + list(node.comparators[:-1])
        for left, op, comp in zip(lefts, node.ops, node.comparators):
            if isinstance(op, (ast.Is, ast.IsNot)):
                operands = [comp, left]
                for o in operands:
                    if isinstance(o, ast.Constant) and isinstance(
                            o.value, (str, int, float, bytes)) and \
                            not isinstance(o.value, bool):
                        self.add(node, "is-literal",
                                 "`is` comparison with a literal; "
                                 "use == / !=")
                        break
        self.generic_visit(node)

    def _check_defaults(self, node):
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and isinstance(d.func, ast.Name)
                    and d.func.id in ("list", "dict", "set")
                    and not d.args and not d.keywords):
                self.add(d, "mutable-default",
                         "mutable default argument; use None and "
                         "initialize inside")

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Assert(self, node):
        if isinstance(node.test, ast.Tuple) and node.test.elts:
            self.add(node, "assert-tuple",
                     "assert on a non-empty tuple is always true "
                     "(did you mean `assert cond, msg`?)")
        self.generic_visit(node)


def lint_file(path):
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except (OSError, UnicodeDecodeError) as e:
        return [Finding(path, 0, "unreadable", str(e))]
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "syntax-error", e.msg)]
    return Linter(path, tree, source).run()


# ---- Prometheus text-exposition validator ----
#
# The scrape endpoint (binder_tpu/metrics/collector.py expose()) hand-
# renders the text format version 0.0.4; a formatting bug there is
# invisible to every unit test that greps for a substring but breaks
# real Prometheus ingestion silently.  validate_exposition() checks the
# whole grammar plus the semantic invariants a hand-rolled histogram
# can violate: cumulative buckets must be non-decreasing in `le` order,
# the +Inf bucket must exist and equal `_count`, `_sum`/`_count` must
# both be present per label set, counters must be finite and
# non-negative, every sample must belong to a declared # TYPE family,
# and no (name, labelset) may repeat.  Returns a list of
# "line N: message" strings; empty list == valid.  Wired into tier-1
# via tests/test_attribution.py against MetricsCollector.expose().

_METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _parse_label_block(block, errs, lineno):
    """`k="v",k2="v2"` (no surrounding braces) -> tuple of (k, v) pairs,
    validating names, quoting, and escape sequences."""
    pairs = []
    i, n = 0, len(block)
    while i < n:
        j = block.find("=", i)
        if j < 0:
            errs.append(f"line {lineno}: malformed label block "
                        f"{block[i:]!r}")
            return tuple(pairs)
        name = block[i:j]
        if not _LABEL_NAME_RE.match(name):
            errs.append(f"line {lineno}: bad label name {name!r}")
        if j + 1 >= n or block[j + 1] != '"':
            errs.append(f"line {lineno}: label {name!r} value not quoted")
            return tuple(pairs)
        k = j + 2
        val = []
        while k < n:
            c = block[k]
            if c == "\\":
                if k + 1 >= n or block[k + 1] not in ('\\', '"', 'n'):
                    errs.append(f"line {lineno}: bad escape in label "
                                f"{name!r}")
                    return tuple(pairs)
                val.append({"\\": "\\", '"': '"', "n": "\n"}[block[k + 1]])
                k += 2
            elif c == '"':
                break
            else:
                val.append(c)
                k += 1
        else:
            errs.append(f"line {lineno}: unterminated label value for "
                        f"{name!r}")
            return tuple(pairs)
        pairs.append((name, "".join(val)))
        i = k + 1
        if i < n:
            if block[i] != ",":
                errs.append(f"line {lineno}: expected ',' between labels")
                return tuple(pairs)
            i += 1
    return tuple(pairs)


def _parse_value(tok, errs, lineno, what="value"):
    if tok in ("+Inf", "-Inf", "Inf", "NaN"):
        return float(tok.replace("Inf", "inf").replace("NaN", "nan"))
    try:
        return float(tok)
    except ValueError:
        errs.append(f"line {lineno}: unparseable {what} {tok!r}")
        return None


def validate_exposition(text):
    """Validate Prometheus text format 0.0.4.  Returns error strings
    ("line N: msg"); an empty list means the exposition is valid."""
    errs = []
    if text and not text.endswith("\n"):
        errs.append("line 0: exposition must end with a newline")
    types = {}          # family name -> declared type
    helps = set()
    samples = {}        # (sample name, label tuple) -> (lineno, value)
    family_of = {}      # sample name -> family (for suffix resolution)
    order = []          # (family, labels-without-le, le, value, lineno)
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line != line.strip():
            errs.append(f"line {lineno}: leading/trailing whitespace")
            line = line.strip()
            if not line:
                continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("HELP", "TYPE"):
                if len(parts) < 3 or not _METRIC_NAME_RE.match(parts[2]):
                    errs.append(f"line {lineno}: malformed {parts[1]}")
                    continue
                name = parts[2]
                if parts[1] == "TYPE":
                    kind = parts[3].strip() if len(parts) > 3 else ""
                    if kind not in _TYPES:
                        errs.append(f"line {lineno}: unknown TYPE "
                                    f"{kind!r} for {name}")
                    if name in types:
                        errs.append(f"line {lineno}: duplicate TYPE "
                                    f"for {name}")
                    if any(fam == name for fam in family_of.values()):
                        errs.append(f"line {lineno}: TYPE for {name} "
                                    "after its samples")
                    types[name] = kind
                else:
                    if name in helps:
                        errs.append(f"line {lineno}: duplicate HELP "
                                    f"for {name}")
                    helps.add(name)
            continue   # other comments are free-form
        # sample line: name[{labels}] value [timestamp]
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                errs.append(f"line {lineno}: unbalanced braces")
                continue
            name = line[:brace]
            labels = _parse_label_block(line[brace + 1:close], errs,
                                        lineno)
            rest = line[close + 1:].split()
        else:
            toks = line.split()
            name, labels, rest = toks[0], (), toks[1:]
        if not _METRIC_NAME_RE.match(name):
            errs.append(f"line {lineno}: bad metric name {name!r}")
            continue
        if len(rest) not in (1, 2):
            errs.append(f"line {lineno}: expected 'name value "
                        "[timestamp]'")
            continue
        value = _parse_value(rest[0], errs, lineno)
        if len(rest) == 2 and _parse_value(
                rest[1], errs, lineno, "timestamp") is None:
            continue
        # resolve the family: histogram/summary samples carry suffixes
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:len(name) - len(suffix)]
            if name.endswith(suffix) and types.get(base) in (
                    "histogram", "summary"):
                family = base
                break
        if family not in types:
            errs.append(f"line {lineno}: sample {name!r} has no "
                        "preceding # TYPE")
        family_of[name] = family
        key = (name, labels)
        if key in samples:
            errs.append(f"line {lineno}: duplicate sample {name}"
                        f"{dict(labels)!r} (first at line "
                        f"{samples[key][0]})")
        samples[key] = (lineno, value)
        kind = types.get(family)
        if kind == "counter" and value is not None and \
                not (value >= 0.0 and value == value and
                     value != float("inf")):
            errs.append(f"line {lineno}: counter {name} value {rest[0]} "
                        "not a finite non-negative number")
        if kind == "histogram" and name.endswith("_bucket"):
            le = dict(labels).get("le")
            if le is None:
                errs.append(f"line {lineno}: histogram bucket without "
                            "le label")
            else:
                bare = tuple(p for p in labels if p[0] != "le")
                order.append((family, bare, le, value, lineno))
    # histogram semantics per (family, label set)
    series = {}
    for family, bare, le, value, lineno in order:
        series.setdefault((family, bare), []).append((le, value, lineno))
    for (family, bare), cells in series.items():
        prev = None
        inf_val = None
        for le, value, lineno in cells:
            lef = _parse_value(le, errs, lineno, "le bound")
            if lef is None or value is None:
                continue
            if prev is not None and lef <= prev[0]:
                errs.append(f"line {lineno}: {family} buckets out of "
                            f"le order ({le!r} after {prev[1]!r})")
            if prev is not None and value < prev[2]:
                errs.append(f"line {lineno}: {family} cumulative bucket "
                            f"count decreases at le={le!r}")
            prev = (lef, le, value)
            if lef == float("inf"):
                inf_val = value
        if inf_val is None:
            errs.append(f"{family}{dict(bare)!r}: no le=\"+Inf\" bucket")
        cnt = samples.get((family + "_count", bare))
        if cnt is None:
            errs.append(f"{family}{dict(bare)!r}: missing _count")
        elif inf_val is not None and cnt[1] != inf_val:
            errs.append(f"line {cnt[0]}: {family}_count {cnt[1]:g} != "
                        f"+Inf bucket {inf_val:g}")
        if (family + "_sum", bare) not in samples:
            errs.append(f"{family}{dict(bare)!r}: missing _sum")
    return errs


# ---- introspection snapshot-schema validator ----
#
# The /status endpoint (binder_tpu/introspect/status.py) is consumed by
# bin/bstat and by operators' jq one-liners; a silently dropped or
# retyped field breaks both without failing any substring-grepping
# test.  validate_status_snapshot() pins the schema: required sections,
# required keys per section, and value types (None allowed only where
# the schema says nullable).  Returns "path: message" strings; empty
# list == valid.  Wired into tier-1 via tests/test_introspect.py
# against a live HTTP endpoint, and into `make status-smoke`.

_NUM = (int, float)
# section -> {key: (types, nullable)}
_SNAPSHOT_SCHEMA = {
    "service": {
        "name": (str, False), "pid": (int, False),
        "version": (int, False), "uptime_seconds": (_NUM, False),
        "generated_at": (_NUM, False),
    },
    "store": {
        "backend": (str, True), "state": (str, False),
        "connected": (bool, False),
        "disconnected_seconds": (_NUM, True),
        "session_establishments": (int, False),
        "transitions": (list, False),
    },
    "mirror": {
        "ready": (bool, False), "domain": (str, True),
        "generation": (int, False), "epoch": (int, False),
        "nodes": (int, False), "names": (int, False),
        "reverse_entries": (int, False),
        "interned_names": (int, False),
        "staleness_seconds": (_NUM, True),
        "last_rebuild_age_seconds": (_NUM, True),
        "rebuild": (dict, False),
    },
    "answer_cache": {
        "size": (int, False), "entries": (int, False),
        "hits": (int, False), "misses": (int, False),
        "hit_ratio": (_NUM, False), "invalidations": (int, False),
        "expiry_ms": (_NUM, False), "neg_hits": (int, False),
        "compiled_entries": (int, False),
        "compiled_serves": (int, False),
        "compiled_installs": (int, False),
    },
    "inflight": {
        "count": (int, False), "queries": (list, False),
    },
    "tcp": {
        "open_conns": (int, False), "max_conns": (int, False),
        "idle_timeout_seconds": (_NUM, False),
        "max_write_buffer": (int, False),
        "cap_refusals": (int, False), "accepts": (int, False),
        "fast_serves": (int, False), "promotions": (int, False),
        "oneshot_closes": (int, False), "idle_timeouts": (int, False),
        "slow_reader_drops": (int, False),
        "coalesced_writes": (int, False),
        "coalesced_frames": (int, False), "half_closes": (int, False),
        "rst_drops": (int, False),
    },
}
_SESSION_STATES = ("never-connected", "connected", "degraded", "expired",
                   "closed")
_INFLIGHT_KEYS = ("trace", "name", "type", "client", "protocol",
                  "age_ms", "phase", "phases")
_TRANSITION_KEYS = ("t_wall", "age_seconds", "from", "to", "reason")


def _check_keys(obj, schema, where, errs):
    for key, (types, nullable) in schema.items():
        if key not in obj:
            errs.append(f"{where}: missing key {key!r}")
            continue
        val = obj[key]
        if val is None:
            if not nullable:
                errs.append(f"{where}.{key}: null not allowed")
            continue
        if not isinstance(val, types):
            errs.append(f"{where}.{key}: expected "
                        f"{getattr(types, '__name__', types)}, got "
                        f"{type(val).__name__}")


def validate_status_snapshot(snap):
    """Validate an introspection snapshot (parsed JSON).  Returns error
    strings; an empty list means the snapshot is schema-complete."""
    errs = []
    if not isinstance(snap, dict):
        return [f"snapshot: expected object, got {type(snap).__name__}"]
    for section, schema in _SNAPSHOT_SCHEMA.items():
        sub = snap.get(section)
        if not isinstance(sub, dict):
            errs.append(f"{section}: missing or not an object")
            continue
        _check_keys(sub, schema, section, errs)
    # nullable top-level sections must still be PRESENT (consumers key
    # on them to know the feature is off, not mistyped)
    for section in ("recursion", "precompile", "verify", "loop",
                    "flight_recorder", "policy"):
        if section not in snap:
            errs.append(f"{section}: key must be present (null when "
                        "the subsystem is off)")
        elif snap[section] is not None and not isinstance(
                snap[section], dict):
            errs.append(f"{section}: expected object or null")
    store = snap.get("store")
    if isinstance(store, dict):
        if store.get("state") not in _SESSION_STATES:
            errs.append(f"store.state: unknown state "
                        f"{store.get('state')!r}")
        for i, tr in enumerate(store.get("transitions") or []):
            if not isinstance(tr, dict):
                errs.append(f"store.transitions[{i}]: not an object")
                continue
            for key in _TRANSITION_KEYS:
                if key not in tr:
                    errs.append(f"store.transitions[{i}]: missing "
                                f"{key!r}")
    infl = snap.get("inflight")
    if isinstance(infl, dict) and isinstance(infl.get("queries"), list):
        if infl.get("count") != len(infl["queries"]):
            errs.append("inflight.count != len(inflight.queries)")
        for i, q in enumerate(infl["queries"]):
            if not isinstance(q, dict):
                errs.append(f"inflight.queries[{i}]: not an object")
                continue
            for key in _INFLIGHT_KEYS:
                if key not in q:
                    errs.append(f"inflight.queries[{i}]: missing "
                                f"{key!r}")
    loop = snap.get("loop")
    if isinstance(loop, dict):
        for key in ("interval_seconds", "stall_threshold_seconds",
                    "samples", "stalls", "last_lag_seconds",
                    "max_lag_seconds"):
            if key not in loop:
                errs.append(f"loop: missing {key!r}")
    fr = snap.get("flight_recorder")
    if isinstance(fr, dict):
        for key in ("capacity", "recorded", "dropped", "by_type",
                    "events"):
            if key not in fr:
                errs.append(f"flight_recorder: missing {key!r}")
        seqs = [ev.get("seq") for ev in fr.get("events") or []
                if isinstance(ev, dict)]
        if seqs != sorted(seqs):
            errs.append("flight_recorder.events: seq not ascending")
    mirror = snap.get("mirror")
    if isinstance(mirror, dict) and isinstance(mirror.get("rebuild"),
                                               dict):
        for key in ("pending", "chunks", "last_duration_seconds"):
            if key not in mirror["rebuild"]:
                errs.append(f"mirror.rebuild: missing {key!r}")
    pc = snap.get("precompile")
    if isinstance(pc, dict):
        for key in ("queue_depth", "max_pending", "batch", "compiled",
                    "declined", "shed", "seed_remaining"):
            if key not in pc:
                errs.append(f"precompile: missing {key!r}")
    vf = snap.get("verify")
    if isinstance(vf, dict):
        for key in ("enabled", "checks", "violations", "skipped",
                    "queue_depth", "audit", "recent_violations",
                    "propagation"):
            if key not in vf:
                errs.append(f"verify: missing {key!r}")
        audit = vf.get("audit")
        if isinstance(audit, dict):
            for key in ("passes", "pending", "interval_seconds",
                        "sample"):
                if key not in audit:
                    errs.append(f"verify.audit: missing {key!r}")
        prop = vf.get("propagation")
        if isinstance(prop, dict):
            for key in ("observed", "stages", "slowest"):
                if key not in prop:
                    errs.append(f"verify.propagation: missing {key!r}")
    pol = snap.get("policy")
    if isinstance(pol, dict):
        for key in ("degradation", "admission", "rrl", "breakers_open"):
            if key not in pol:
                errs.append(f"policy: missing {key!r}")
        deg = pol.get("degradation")
        if isinstance(deg, dict):
            for key in ("state", "state_since_seconds",
                        "max_staleness_seconds",
                        "stale_ttl_clamp_seconds", "exhausted_action",
                        "mirror_staleness_seconds", "stale_served",
                        "withheld", "transitions"):
                if key not in deg:
                    errs.append(f"policy.degradation: missing {key!r}")
            if deg.get("state") not in ("fresh", "stale-serving",
                                        "stale-exhausted", None):
                errs.append(f"policy.degradation.state: unknown state "
                            f"{deg.get('state')!r}")
        adm = pol.get("admission")
        if isinstance(adm, dict):
            for key in ("max_inflight", "inflight", "recursion_rate",
                        "recursion_burst", "clients_tracked", "shed"):
                if key not in adm:
                    errs.append(f"policy.admission: missing {key!r}")
        rrl = pol.get("rrl")
        if isinstance(rrl, dict):
            for key in ("enabled", "responses_per_second", "burst",
                        "slip_ratio", "buckets", "hot", "responses",
                        "slipped", "dropped", "evictions",
                        "allowlist", "allowlisted", "adaptive",
                        "adapted_buckets", "adaptations",
                        "false_positives"):
                if key not in rrl:
                    errs.append(f"policy.rrl: missing {key!r}")
    return errs


# ---- mutation-time precompiler metrics validator ----
#
# The precompiler's operational story lives in its metrics: compiled /
# declined / shed counters plus the live queue-depth gauge.  An exporter
# bug that silently dropped one of them would leave storm shedding
# invisible — exactly the failure mode the bounded queue exists to
# surface.  validate_precompile_metrics() checks a scrape exposition for
# the full binder_precompile_* family with the right TYPEs.  Wired into
# tier-1 via tests/test_precompile.py alongside validate_exposition.

_PRECOMPILE_FAMILIES = {
    "binder_precompile_compiled": "counter",
    "binder_precompile_declined": "counter",
    "binder_precompile_shed": "counter",
    "binder_precompile_queue_depth": "gauge",
    "binder_precompile_serves": "counter",
}


def validate_precompile_metrics(text):
    """Validate that a Prometheus exposition carries the complete
    ``binder_precompile_*`` family (correct TYPE declarations and at
    least one sample each).  Returns error strings; empty == valid."""
    errs = list(validate_exposition(text))
    types = {}
    sampled = set()
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("# TYPE") and len(parts) >= 4:
            types[parts[2]] = parts[3]
        elif line and not line.startswith("#") and parts:
            name = parts[0].split("{", 1)[0]
            sampled.add(name)
    for family, kind in _PRECOMPILE_FAMILIES.items():
        if family not in types:
            errs.append(f"{family}: missing # TYPE declaration")
        elif types[family] != kind:
            errs.append(f"{family}: declared {types[family]!r}, "
                        f"expected {kind!r}")
        if family not in sampled:
            errs.append(f"{family}: no samples in exposition")
    return errs


# ---- degradation / chaos metrics validator ----
#
# The degradation policy engine's whole point is that failure behavior
# is *observable*: binder_degraded_state is what the alert rules watch,
# binder_breaker_state is how an operator sees a dead peer being
# routed around, binder_shed_total is the only record of refused load.
# An exporter bug dropping any of them makes a degraded binder look
# healthy — the exact silent failure this PR exists to kill.
# validate_degradation_metrics() checks a scrape exposition for the
# full family set with the right TYPEs, the label pins the dashboards
# key on, and at least one sample each (every series is materialized
# at registration, so absence is always a bug).  Wired into tier-1 via
# tests/test_chaos.py and into `make chaos-smoke`.

_DEGRADATION_FAMILIES = {
    "binder_degraded_state": "gauge",
    "binder_breaker_state": "gauge",
    "binder_shed_total": "counter",
    "binder_stale_served_total": "counter",
    "binder_stale_withheld_total": "counter",
}
#: label values that must exist from scrape 1 (family -> label -> values)
_DEGRADATION_LABEL_PINS = {
    "binder_shed_total": ("reason", ("inflight-overflow",
                                     "recursion-ratelimit")),
    "binder_breaker_state": ("peer", ("(max)",)),
}


def validate_degradation_metrics(text):
    """Validate that a Prometheus exposition carries the complete
    degradation/shedding family set (correct TYPE declarations, pinned
    label values, at least one sample each).  Returns error strings;
    empty == valid.  Scope: a FULLY configured binder — degradation +
    admission blocks on AND recursion configured (the breaker family
    registers with the recursion layer; a binder without upstreams has
    nothing to break and legitimately lacks it)."""
    errs = list(validate_exposition(text))
    types = {}
    labels_seen = {}    # family -> {label name -> set(values)}
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("# TYPE") and len(parts) >= 4:
            types[parts[2]] = parts[3]
        elif line and not line.startswith("#") and parts:
            brace = line.find("{")
            name = line[:brace] if brace >= 0 else parts[0]
            fam_labels = labels_seen.setdefault(name, {})
            if brace >= 0:
                close = line.rfind("}")
                for lname, lval in _parse_label_block(
                        line[brace + 1:close], [], 0):
                    fam_labels.setdefault(lname, set()).add(lval)
            else:
                fam_labels.setdefault(None, set()).add("")
    for family, kind in _DEGRADATION_FAMILIES.items():
        if family not in types:
            errs.append(f"{family}: missing # TYPE declaration")
        elif types[family] != kind:
            errs.append(f"{family}: declared {types[family]!r}, "
                        f"expected {kind!r}")
        if family not in labels_seen:
            errs.append(f"{family}: no samples in exposition")
    for family, (label, values) in _DEGRADATION_LABEL_PINS.items():
        have = labels_seen.get(family, {}).get(label, set())
        for val in values:
            if val not in have:
                errs.append(f"{family}: missing pinned series "
                            f"{label}={val!r}")
    return errs


# ---- TCP stream-lane metrics validator ----
#
# The stream lane's performance story is only auditable through its
# counters: fast_serves vs promotions names whether the accept fast
# path is actually carrying the one-shot population, and the drop
# counters (idle / slow-reader / cap) are the only record of shed
# connections.  validate_tcp_metrics() checks a scrape exposition for
# the full binder_tcp_* family with the right TYPEs and at least one
# sample each (every series is materialized at registration, so absence
# is always an exporter bug).  Wired into tier-1 via
# tests/test_tcp_stream.py and into `make tcp-smoke`.

_TCP_FAMILIES = {
    "binder_tcp_accepts": "counter",
    "binder_tcp_fast_serves": "counter",
    "binder_tcp_promotions": "counter",
    "binder_tcp_oneshot_closes": "counter",
    "binder_tcp_idle_timeouts": "counter",
    "binder_tcp_slow_reader_drops": "counter",
    "binder_tcp_coalesced_writes": "counter",
    "binder_tcp_coalesced_frames": "counter",
    "binder_tcp_half_closes": "counter",
    "binder_tcp_rst_drops": "counter",
    "binder_tcp_cap_refusals": "counter",
    "binder_tcp_open_conns": "gauge",
}


def validate_tcp_metrics(text):
    """Validate that a Prometheus exposition carries the complete
    ``binder_tcp_*`` family (correct TYPE declarations and at least one
    sample each).  Returns error strings; empty == valid."""
    errs = list(validate_exposition(text))
    types = {}
    sampled = set()
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("# TYPE") and len(parts) >= 4:
            types[parts[2]] = parts[3]
        elif line and not line.startswith("#") and parts:
            sampled.add(parts[0].split("{", 1)[0])
    for family, kind in _TCP_FAMILIES.items():
        if family not in types:
            errs.append(f"{family}: missing # TYPE declaration")
        elif types[family] != kind:
            errs.append(f"{family}: declared {types[family]!r}, "
                        f"expected {kind!r}")
        if family not in sampled:
            errs.append(f"{family}: no samples in exposition")
    return errs


# -- shard-mode metrics (binder_tpu/shard, docs/observability.md) ------
#
# The supervisor aggregates N workers into the binder_shard_* family:
# per-shard series MUST carry a `shard` label (an unlabeled sample
# would silently sum incomparable processes in PromQL), every family
# must have the right TYPE, and every series must exist from scrape 1
# (the supervisor registers all N label sets at startup, so absence is
# always an exporter bug).  Wired into tier-1 via tests/test_shards.py
# and into `make shard-smoke`.

_SHARD_FAMILIES = {
    "binder_shards": ("gauge", False),
    "binder_shard_up": ("gauge", True),
    "binder_shard_pid": ("gauge", True),
    "binder_shard_generation": ("gauge", True),
    "binder_shard_ready": ("gauge", True),
    "binder_shard_respawns": ("counter", True),
    "binder_shard_requests": ("counter", True),
    "binder_shard_rolls_total": ("counter", True),
    "binder_shard_roll_aborts_total": ("counter", False),
}


def validate_shard_metrics(text):
    """Validate that a Prometheus exposition carries the complete
    ``binder_shard_*`` family: correct TYPE declarations, at least one
    sample each, and a ``shard`` label on every per-shard series.
    Returns error strings; empty == valid."""
    errs = list(validate_exposition(text))
    types = {}
    samples = {}
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("# TYPE") and len(parts) >= 4:
            types[parts[2]] = parts[3]
        elif line and not line.startswith("#") and parts:
            name, _, labels = parts[0].partition("{")
            samples.setdefault(name, []).append(labels)
    for family, (kind, per_shard) in _SHARD_FAMILIES.items():
        if family not in types:
            errs.append(f"{family}: missing # TYPE declaration")
        elif types[family] != kind:
            errs.append(f"{family}: declared {types[family]!r}, "
                        f"expected {kind!r}")
        if family not in samples:
            errs.append(f"{family}: no samples in exposition")
        elif per_shard:
            for labels in samples[family]:
                # parse actual label NAMES ("notshard" must not pass a
                # substring check for "shard")
                names = {pair.partition("=")[0]
                         for pair in labels.partition("}")[0].split(",")
                         if pair}
                if "shard" not in names:
                    errs.append(f"{family}: sample missing the "
                                f"`shard` label")
                    break
    return errs


# -- mirror / zone-scale metrics (ISSUE 7, docs/observability.md) ------
#
# The million-name story is told by the binder_mirror_* family (name
# count, interned-pool size, chunked-rebuild progress/duration) plus
# binder_udp_late_drops_total (late responses dropped at a full socket
# buffer — the drop path that used to be a silent debug line).  Every
# family must carry the right TYPE and at least one sample, and none of
# the per-binder series may carry stray labels (an accidental label
# would split the one-series-per-process contract PromQL dashboards sum
# over).  Wired into tier-1 via tests/test_zone_scale.py and into
# `make zone-smoke`.

_MIRROR_FAMILIES = {
    "binder_mirror_staleness_seconds": "gauge",
    "binder_mirror_names": "gauge",
    "binder_mirror_interned_names": "gauge",
    "binder_mirror_rebuild_pending": "gauge",
    "binder_mirror_rebuild_seconds": "gauge",
    "binder_mirror_rebuild_chunks": "counter",
    "binder_udp_late_drops_total": "counter",
}

#: labels the collector's static set may legitimately add to every
#: series; anything else on a mirror-family sample is a pin violation
_MIRROR_ALLOWED_LABELS = frozenset(
    ("datacenter", "instance", "server", "service", "port"))


def validate_mirror_metrics(text):
    """Validate that a Prometheus exposition carries the complete
    ``binder_mirror_*`` / zone-scale family (plus the late-drop
    counter): correct TYPE declarations, at least one sample each, and
    no labels beyond the collector's static set.  Returns error
    strings; empty == valid."""
    errs = list(validate_exposition(text))
    types = {}
    samples = {}
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("# TYPE") and len(parts) >= 4:
            types[parts[2]] = parts[3]
        elif line and not line.startswith("#") and parts:
            name, _, labels = parts[0].partition("{")
            samples.setdefault(name, []).append(labels)
    for family, kind in _MIRROR_FAMILIES.items():
        if family not in types:
            errs.append(f"{family}: missing # TYPE declaration")
        elif types[family] != kind:
            errs.append(f"{family}: declared {types[family]!r}, "
                        f"expected {kind!r}")
        if family not in samples:
            errs.append(f"{family}: no samples in exposition")
            continue
        for labels in samples[family]:
            names = {pair.partition("=")[0]
                     for pair in labels.partition("}")[0].split(",")
                     if pair}
            stray = names - _MIRROR_ALLOWED_LABELS
            if stray:
                errs.append(f"{family}: unexpected label(s) "
                            f"{sorted(stray)}")
                break
    return errs


# -- federation metrics (ISSUE 11, docs/federation.md) ----------------
#
# The multi-DC story is told by the binder_federation_* family (registry
# size, per-DC forward counts, the foreign-answer cache's stale/withheld
# split, budget clamps, failover convergence) plus the recursion
# single-flight counter.  Forward counts are the only per-DC series and
# must carry the `dc` label; everything else is one series per process.
# Wired into tier-1 via tests/test_federation.py and into
# `make federation-smoke`.

_FEDERATION_FAMILIES = {
    "binder_federation_dcs": ("gauge", False),
    "binder_federation_convergence_seconds": ("gauge", False),
    "binder_federation_forwards_total": ("counter", True),
    "binder_federation_foreign_hits_total": ("counter", False),
    "binder_federation_foreign_stale_served_total": ("counter", False),
    "binder_federation_foreign_withheld_total": ("counter", False),
    "binder_federation_budget_clamped_total": ("counter", False),
    "binder_federation_failovers_total": ("counter", False),
    "binder_recursion_coalesced_total": ("counter", False),
}


def validate_federation_metrics(text):
    """Validate that a Prometheus exposition carries the complete
    ``binder_federation_*`` family (plus the recursion single-flight
    counter): correct TYPE declarations, at least one sample each, a
    ``dc`` label on every forward-count series, and no labels beyond
    the collector's static set elsewhere.  Returns error strings;
    empty == valid."""
    errs = list(validate_exposition(text))
    types = {}
    samples = {}
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("# TYPE") and len(parts) >= 4:
            types[parts[2]] = parts[3]
        elif line and not line.startswith("#") and parts:
            name, _, labels = parts[0].partition("{")
            samples.setdefault(name, []).append(labels)
    for family, (kind, per_dc) in _FEDERATION_FAMILIES.items():
        if family not in types:
            errs.append(f"{family}: missing # TYPE declaration")
        elif types[family] != kind:
            errs.append(f"{family}: declared {types[family]!r}, "
                        f"expected {kind!r}")
        if family not in samples:
            errs.append(f"{family}: no samples in exposition")
            continue
        for labels in samples[family]:
            # parse actual label NAMES ("notdc" must not pass a
            # substring check for "dc")
            names = {pair.partition("=")[0]
                     for pair in labels.partition("}")[0].split(",")
                     if pair}
            if per_dc:
                if "dc" not in names:
                    errs.append(f"{family}: sample missing the "
                                f"`dc` label")
                    break
            else:
                stray = names - _MIRROR_ALLOWED_LABELS
                if stray:
                    errs.append(f"{family}: unexpected label(s) "
                                f"{sorted(stray)}")
                    break
    return errs


# -- RRL / hostile-traffic metrics (ISSUE 12, docs/operations.md) -----
#
# The hostile-internet posture is told by the binder_rrl_* family
# (responses admitted / slipped / dropped / bucket evictions, live
# bucket count, the `active` flood flag) plus the
# binder_shed_total{reason="response-ratelimit"} series the drops feed.
# Wired into tier-1 via tests/test_hostile.py and into
# `make hostile-smoke`.

_RRL_FAMILIES = {
    "binder_rrl_responses_total": "counter",
    "binder_rrl_slipped_total": "counter",
    "binder_rrl_dropped_total": "counter",
    "binder_rrl_evictions_total": "counter",
    "binder_rrl_allowlisted_total": "counter",
    "binder_rrl_adaptations_total": "counter",
    "binder_rrl_false_positives_total": "counter",
    "binder_rrl_buckets": "gauge",
    "binder_rrl_active": "gauge",
    "binder_rrl_adapted_buckets": "gauge",
}


def validate_rrl_metrics(text):
    """Validate that a Prometheus exposition carries the complete
    ``binder_rrl_*`` family plus the response-ratelimit shed series:
    correct TYPE declarations, at least one sample each, and no labels
    beyond the collector's static set.  Returns error strings;
    empty == valid."""
    errs = list(validate_exposition(text))
    types = {}
    samples = {}
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("# TYPE") and len(parts) >= 4:
            types[parts[2]] = parts[3]
        elif line and not line.startswith("#") and parts:
            name, _, labels = parts[0].partition("{")
            samples.setdefault(name, []).append(labels)
    for family, kind in _RRL_FAMILIES.items():
        if family not in types:
            errs.append(f"{family}: missing # TYPE declaration")
        elif types[family] != kind:
            errs.append(f"{family}: declared {types[family]!r}, "
                        f"expected {kind!r}")
        if family not in samples:
            errs.append(f"{family}: no samples in exposition")
            continue
        for labels in samples[family]:
            names = {pair.partition("=")[0]
                     for pair in labels.partition("}")[0].split(",")
                     if pair}
            stray = names - _MIRROR_ALLOWED_LABELS
            if stray:
                errs.append(f"{family}: unexpected label(s) "
                            f"{sorted(stray)}")
                break
    # the drop path must surface in the shared shed accounting too:
    # operators alert on binder_shed_total, not per-family counters
    if not any(parts and parts[0].startswith("binder_shed_total{")
               and 'reason="response-ratelimit"' in parts[0]
               for parts in (ln.split() for ln in text.splitlines())
               if parts and not parts[0].startswith("#")):
        errs.append('binder_shed_total: missing the '
                    'reason="response-ratelimit" series')
    return errs


# -- serving-plane verification metrics (ISSUE 16) --------------------
#
# The checker's whole value is that silence is never ambiguous: every
# invariant's check/violation/skip series must exist from scrape 1
# (zero-seeded at registration), and the propagation histogram must
# carry every datapath stage before the first mutation.  An exporter
# bug dropping a series would make "no violations" indistinguishable
# from "not checking" — the exact failure the family exists to rule
# out.  Wired into tier-1 via tests/test_verify.py and into
# `make verify-smoke`.

_VERIFY_FAMILIES = {
    "binder_verify_checks_total": "counter",
    "binder_verify_violations_total": "counter",
    "binder_verify_skipped_total": "counter",
    "binder_verify_queue_depth": "gauge",
    "binder_propagation_seconds": "histogram",
}
#: the invariant catalog (binder_tpu/verify/checker.py INVARIANTS) —
#: every value pinned on all three counters; the skip counter also
#: carries the queue-shed series
_VERIFY_INVARIANTS = ("dangling-srv", "ptr-coherence", "compiled-bytes",
                      "replica-digest", "stale-epoch")
#: the propagation stage catalog (binder_tpu/verify/tracer.py STAGES)
_VERIFY_STAGES = ("mirror-apply", "shard-frame", "replica-apply",
                  "precompile-render", "compiled-install",
                  "native-install")


def validate_verify_metrics(text):
    """Validate that a Prometheus exposition carries the complete
    ``binder_verify_*`` family plus the per-stage propagation
    histogram: correct TYPE declarations, at least one sample each,
    every invariant pinned on the three counters (queue-shed on the
    skip counter), and every stage pinned on the histogram.  Returns
    error strings; empty == valid."""
    errs = list(validate_exposition(text))
    types = {}
    labels_seen = {}    # family -> {label name -> set(values)}
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("# TYPE") and len(parts) >= 4:
            types[parts[2]] = parts[3]
        elif line and not line.startswith("#") and parts:
            brace = line.find("{")
            name = line[:brace] if brace >= 0 else parts[0]
            # histogram series expose under <fam>_bucket/_sum/_count
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) \
                        and name[:-len(suffix)] in _VERIFY_FAMILIES:
                    name = name[:-len(suffix)]
                    break
            fam_labels = labels_seen.setdefault(name, {})
            if brace >= 0:
                close = line.rfind("}")
                for lname, lval in _parse_label_block(
                        line[brace + 1:close], [], 0):
                    fam_labels.setdefault(lname, set()).add(lval)
            else:
                fam_labels.setdefault(None, set()).add("")
    for family, kind in _VERIFY_FAMILIES.items():
        if family not in types:
            errs.append(f"{family}: missing # TYPE declaration")
        elif types[family] != kind:
            errs.append(f"{family}: declared {types[family]!r}, "
                        f"expected {kind!r}")
        if family not in labels_seen:
            errs.append(f"{family}: no samples in exposition")
    for family in ("binder_verify_checks_total",
                   "binder_verify_violations_total",
                   "binder_verify_skipped_total"):
        have = labels_seen.get(family, {}).get("invariant", set())
        for inv in _VERIFY_INVARIANTS:
            if inv not in have:
                errs.append(f"{family}: missing pinned series "
                            f"invariant={inv!r}")
    if "queue-shed" not in labels_seen.get(
            "binder_verify_skipped_total", {}).get("invariant", set()):
        errs.append("binder_verify_skipped_total: missing pinned "
                    "series invariant='queue-shed'")
    have = labels_seen.get(
        "binder_propagation_seconds", {}).get("stage", set())
    for stage in _VERIFY_STAGES:
        if stage not in have:
            errs.append(f"binder_propagation_seconds: missing pinned "
                        f"series stage={stage!r}")
    return errs


def is_python_script(path):
    if path.endswith(".py"):
        return True
    try:
        with open(path, "rb") as f:
            head = f.read(64)
        return head.startswith(b"#!") and b"python" in head.splitlines()[0]
    except OSError:
        return False


def collect(paths):
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                for fn in sorted(files):
                    full = os.path.join(root, fn)
                    if is_python_script(full):
                        out.append(full)
        else:
            if is_python_script(p):
                out.append(p)
    return out


def main(argv):
    paths = argv or ["binder_tpu", "tests", "bin", "tools",
                     "bench.py", "bench_impl.py", "__graft_entry__.py"]
    files = collect(paths)
    if not files:
        print("lint: no files found", file=sys.stderr)
        return 2
    findings = []
    for path in files:
        findings.extend(lint_file(path))
    for f in findings:
        print(f)
    if findings:
        print(f"lint: {len(findings)} finding(s) in {len(files)} files",
              file=sys.stderr)
        return 1
    print(f"lint: ok ({len(files)} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
