#!/usr/bin/env python3
"""Report which independent-conformance tiers ran vs skipped (VERDICT r3
item 4): a silently skipped tier must be visible in the CI log, because
the reference's whole test philosophy rests on independent clients
(reference test/dig.js:109-134, test/helper.js:53-61) and a silently
absent one voids that guarantee without anyone noticing.

Usage: conformance_tiers.py <junit.xml> [--strict]

Reads the junit report the main `make test` pytest run already emitted
— ground truth per tier without re-running anything (a re-run would
rewrite /etc/resolv.conf and bind port 53 a second time), and a tier
that skipped at RUNTIME (e.g. port 53 already bound) reports as
skipped even though its static gate was open.  Test failures are the
pytest invocation's own exit code; this tool only classifies outcomes.

Exit status: 0 normally; with --strict, 1 unless at least one
independent DNS *client* tier (dig or glibc getent) actually passed —
the ZooKeeper tier exercises the store client, not the DNS codec, and
does not satisfy the gate.  An explicit BINDER_LIBC_CONFORMANCE=0
waives the strict gate (informed operator opt-out) with a visible note.
"""
import os
import sys
import xml.etree.ElementTree as ET

# tier -> (module, test class) that implements it
TIERS = [
    ("rfc-golden-vectors", "tests.test_conformance", "TestGoldenVectors"),
    ("dig(1)", "tests.test_conformance", "TestDigConformance"),
    ("glibc-getent", "tests.test_conformance", "TestLibcConformance"),
    ("glibc-libresolv", "tests.test_conformance",
     "TestLibresolvConformance"),
    ("real-zookeeper", "tests.test_conformance", "TestRealZooKeeper"),
    ("real-systemd", "tests.test_systemd_real_conformance",
     "TestRealSystemd"),
]
DNS_CLIENT_TIERS = {"dig(1)", "glibc-getent", "glibc-libresolv"}
MODULES = {m for _, m, _ in TIERS}


def tier_outcomes(junit_path: str):
    """(module, class) -> [passed, failed, skip_reasons], conformance
    testcases only."""
    out = {}
    for case in ET.parse(junit_path).getroot().iter("testcase"):
        classname = case.get("classname", "")
        if "." not in classname:
            continue
        module, cls = classname.rsplit(".", 1)
        if module not in MODULES:
            continue
        rec = out.setdefault((module, cls), [0, 0, []])
        skip = case.find("skipped")
        if skip is not None:
            rec[2].append(skip.get("message") or "skipped")
        elif case.find("failure") is not None or \
                case.find("error") is not None:
            rec[1] += 1
        else:
            rec[0] += 1
    return out


def main() -> int:
    args = [a for a in sys.argv[1:] if a != "--strict"]
    strict = "--strict" in sys.argv[1:]
    if len(args) != 1:
        print("usage: conformance_tiers.py <junit.xml> [--strict]",
              file=sys.stderr)
        return 2
    try:
        outcomes = tier_outcomes(args[0])
    except (OSError, ET.ParseError) as e:
        print(f"conformance_tiers: cannot read junit report "
              f"{args[0]}: {e}", file=sys.stderr)
        return 2
    if not outcomes:
        print(f"conformance_tiers: no testcases from {sorted(MODULES)} "
              f"in {args[0]} (wrong file, or the modules failed to "
              f"collect)", file=sys.stderr)
        return 2

    any_dns_client = False
    print("conformance tiers (actual outcomes):")
    for name, module, cls in TIERS:
        passed, failed, reasons = outcomes.get(
            (module, cls), (0, 0, ["not collected"]))
        if failed:
            # already fatal via pytest's own exit status; classify only
            status, why = "FAILED ", f"{failed} test(s) failed"
        elif passed:
            status, why = "ran    ", f"{passed} test(s) passed"
        else:
            status = "SKIPPED"
            why = reasons[0] if reasons else "no tests ran"
        print(f"  {name:<20} {status} — {why}")
        if passed and not failed and name in DNS_CLIENT_TIERS:
            any_dns_client = True

    if not any_dns_client:
        if os.environ.get("BINDER_LIBC_CONFORMANCE") == "0":
            print("  note: independence gate waived — "
                  "BINDER_LIBC_CONFORMANCE=0 set explicitly")
            return 0
        print("  WARNING: no independent DNS client executed; codec "
              "conformance rests on golden vectors alone",
              file=sys.stderr)
        return 1 if strict else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
