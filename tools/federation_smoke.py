#!/usr/bin/env python3
"""Federation smoke: scripted whole-DC loss under continuous load.

Boots TWO datacenter groups in one process, each a real BinderServer
stack over its own fake-store mirror, talking over real loopback UDP:

- DC ``west``: two binders (the peer group) authoritative for
  ``*.west.fedsmoke.test``;
- DC ``east`` (under test): one federated binder — ``/dcs`` registry,
  registry-fed recursion routing, foreign-answer cache — serving its
  own ``*.east.fedsmoke.test`` names locally and forwarding west names
  cross-DC.

While driving a continuous local+foreign query mix, the script kills
the ENTIRE west group mid-run and asserts the PR's acceptance
invariants end to end:

- pre-dark: foreign answers are byte-identical to asking west directly
  (modulo ID and the forwarder's RA bit);
- post-dark: foreign names degrade per policy — previously-seen names
  serve stale (NOERROR, TTL clamped), never-seen names get a
  well-formed REFUSED, and NO query ends in a client-visible timeout;
- local names stay line-rate: east's own-mirror latency after the
  incident is within noise of the pre-dark control;
- failover converges: ``last_convergence_seconds`` is recorded and the
  measured dark->first-stale gap is bounded;
- the scrape passes ``validate_federation_metrics``, the /status
  snapshot carries the federation section with west dark, ``bstat``
  renders it, and the dc-join / dc-dark / federation-failover flight
  events all fired.

Run via ``make federation-smoke`` (30 s) or set
``BINDER_FEDERATION_SECONDS``.  Prints one JSON summary line; exit 0
== all invariants held.
"""
import asyncio
import importlib.machinery
import importlib.util
import json
import os
import socket
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.dns import Message, Rcode, Type, make_query  # noqa: E402
from binder_tpu.federation import Federation  # noqa: E402
from binder_tpu.introspect import FlightRecorder, Introspector  # noqa: E402
from binder_tpu.metrics.collector import MetricsCollector  # noqa: E402
from binder_tpu.recursion import DnsClient, Recursion  # noqa: E402
from binder_tpu.server import BinderServer  # noqa: E402
from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402
from tools.lint import validate_federation_metrics  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOMAIN = "fedsmoke.test"
N_NAMES = 8
STALE_TTL_CLAMP = 15
WEST_PEERS = 2


class Violation(Exception):
    pass


def _percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * p))]


async def _ask(port, name, qtype=Type.A, qid=1, timeout=2.5):
    """One query, one fresh socket, NO retries: a lost answer is the
    exact failure mode this smoke exists to catch (a dark DC must
    never turn into a client-visible timeout)."""
    loop = asyncio.get_running_loop()
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setblocking(False)
    sock.connect(("127.0.0.1", port))
    try:
        sock.send(make_query(name, qtype, qid=qid, rd=True).encode())
        try:
            return await asyncio.wait_for(loop.sock_recv(sock, 4096),
                                          timeout)
        except asyncio.TimeoutError:
            raise Violation(f"client-visible timeout for {name}")
    finally:
        sock.close()


async def _start_west():
    """The west 'cluster': two binders sharing one mirror, each a
    distinct UDP endpoint in east's /dcs peer list."""
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/test/fedsmoke/west",
                   {"type": "service", "service": {"port": 53}})
    for i in range(N_NAMES):
        store.put_json(f"/test/fedsmoke/west/w{i}",
                       {"type": "host",
                        "host": {"address": f"10.50.0.{i + 1}",
                                 "ttl": 60}})
    store.start_session()
    servers = []
    for _ in range(WEST_PEERS):
        s = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                         datacenter_name="west", host="127.0.0.1",
                         port=0, collector=MetricsCollector())
        await s.start()
        servers.append(s)
    return servers


async def _start_east(west_ports):
    """The federated binder under test: /dcs registry on its own
    store, registry-fed routing, short upstream timeout so a dark DC
    is detected in well under the client deadline."""
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/test/fedsmoke/east",
                   {"type": "service", "service": {"port": 53}})
    for i in range(N_NAMES):
        store.put_json(f"/test/fedsmoke/east/l{i}",
                       {"type": "host",
                        "host": {"address": f"10.51.0.{i + 1}",
                                 "ttl": 30}})
    store.put_json("/dcs/east", {"zones": ["east"], "peers": []})
    store.put_json("/dcs/west",
                   {"zones": ["west"],
                    "peers": [f"127.0.0.1:{p}" for p in west_ports]})
    store.start_session()
    collector = MetricsCollector()
    recorder = FlightRecorder()
    federation = Federation(
        store=store, dns_domain=DOMAIN, datacenter_name="east",
        config={"staleTtlClampSeconds": STALE_TTL_CLAMP},
        collector=collector, recorder=recorder)
    federation.start()
    recursion = Recursion(
        zk_cache=cache, dns_domain=DOMAIN, datacenter_name="east",
        source=federation.resolver_source(), nic_provider=lambda: [],
        collector=collector, recorder=recorder,
        client=DnsClient(concurrency=4, timeout=0.3))
    federation.attach(recursion)
    await recursion.wait_ready()
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="east", recursion=recursion,
                          host="127.0.0.1", port=0, collector=collector,
                          flight_recorder=recorder)
    server.federation = federation
    await server.start()
    return server, recursion, federation, recorder


async def _parity_probe(east_port, west_port):
    """Forwarded foreign answers must be byte-identical to asking the
    owning DC directly, modulo the ID and the forwarder's RA bit."""
    for i in range(N_NAMES):
        name = f"w{i}.west.{DOMAIN}"
        a = bytearray(await _ask(east_port, name, qid=700 + i))
        b = bytearray(await _ask(west_port, name, qid=700 + i))
        a[3] |= 0x80
        b[3] |= 0x80
        if a[2:] != b[2:]:
            raise Violation(f"forwarded answer for {name} diverges "
                            f"from the owning DC's")


async def run_federation_incident(duration: float) -> dict:
    west = await _start_west()
    west_ports = [s.udp_port for s in west]
    server, recursion, federation, recorder = await _start_east(west_ports)
    port = server.udp_port

    stats = {"queries": 0, "local_ok": 0, "foreign_ok": 0,
             "foreign_stale": 0}
    lat = {"local_pre": [], "foreign_pre": [],
           "local_post": [], "foreign_post": []}
    dark_at = None
    first_stale_gap = None
    try:
        await _parity_probe(port, west_ports[0])

        t0 = time.monotonic()
        t_dark = t0 + max(1.0, duration * 0.55)
        t_end = t0 + duration
        i = 0
        while time.monotonic() < t_end:
            if dark_at is None and time.monotonic() >= t_dark:
                # the incident: the WHOLE west group goes away at once
                for s in west:
                    await s.stop()
                dark_at = time.monotonic()
            i += 1
            foreign = i % 2 == 0
            name = (f"w{i % N_NAMES}.west.{DOMAIN}" if foreign
                    else f"l{i % N_NAMES}.east.{DOMAIN}")
            stats["queries"] += 1
            start = time.perf_counter()
            data = await _ask(port, name, qid=(i % 0xFFFF) + 1)
            elapsed = time.perf_counter() - start
            msg = Message.decode(data)
            if foreign:
                if msg.rcode != Rcode.NOERROR or not msg.answers:
                    raise Violation(
                        f"foreign {name} got rcode {msg.rcode} "
                        f"({'post' if dark_at else 'pre'}-dark)")
                want = f"10.50.0.{i % N_NAMES + 1}"
                if msg.answers[0].address != want:
                    raise Violation(f"foreign {name} served "
                                    f"{msg.answers[0].address}, "
                                    f"want {want}")
                if dark_at is None:
                    lat["foreign_pre"].append(elapsed)
                else:
                    # stale-served: TTL must be clamped per policy
                    if msg.answers[0].ttl > STALE_TTL_CLAMP:
                        raise Violation(
                            f"post-dark {name} TTL "
                            f"{msg.answers[0].ttl} > clamp "
                            f"{STALE_TTL_CLAMP} (not stale-served?)")
                    if first_stale_gap is None:
                        first_stale_gap = time.monotonic() - dark_at
                    stats["foreign_stale"] += 1
                    lat["foreign_post"].append(elapsed)
                stats["foreign_ok"] += 1
            else:
                if msg.rcode != Rcode.NOERROR or not msg.answers:
                    raise Violation(f"local {name} got rcode {msg.rcode}")
                lat["local_pre" if dark_at is None
                    else "local_post"].append(elapsed)
                stats["local_ok"] += 1
            await asyncio.sleep(duration / 1500.0)

        if dark_at is None or first_stale_gap is None:
            raise Violation("incident never ran: raise the duration")

        # a foreign name the cache has never seen: dark DC, nothing to
        # serve stale -> well-formed REFUSED, still no timeout
        miss = Message.decode(
            await _ask(port, f"never.west.{DOMAIN}", qid=9999))
        if miss.rcode != Rcode.REFUSED:
            raise Violation(f"uncached dark-DC name got rcode "
                            f"{miss.rcode}, want REFUSED")

        # -- local latency stayed line-rate through the incident --
        pre50 = _percentile(lat["local_pre"], 0.50)
        post50 = _percentile(lat["local_post"], 0.50)
        post99 = _percentile(lat["local_post"], 0.99)
        if post50 > max(4 * pre50, pre50 + 0.005):
            raise Violation(
                f"local p50 degraded {pre50 * 1e3:.2f}ms -> "
                f"{post50 * 1e3:.2f}ms while west was dark")
        if post99 > 0.25:
            raise Violation(f"local p99 {post99 * 1e3:.1f}ms post-dark")
        if first_stale_gap > 5.0:
            raise Violation(f"failover took {first_stale_gap:.1f}s to "
                            f"first stale answer")

        # -- observability: scrape, snapshot, bstat, flight events --
        text = server.collector.expose()
        errs = validate_federation_metrics(text)
        if errs:
            raise Violation(f"federation metrics: {errs[:3]}")
        snap = Introspector(server=server, recorder=recorder).snapshot()
        fed = snap.get("federation")
        if not fed:
            raise Violation("/status snapshot has no federation section")
        if fed["dark"] != ["west"]:
            raise Violation(f"snapshot dark set {fed['dark']}, "
                            f"want ['west']")
        if fed["last_convergence_seconds"] is None:
            raise Violation("no failover convergence was recorded")
        loader = importlib.machinery.SourceFileLoader(
            "bstat", os.path.join(ROOT, "bin", "bstat"))
        spec = importlib.util.spec_from_loader("bstat", loader)
        bstat = importlib.util.module_from_spec(spec)
        loader.exec_module(bstat)
        rendered = bstat.render(snap)
        if "federation:" not in rendered or "(DARK)" not in rendered:
            raise Violation("bstat does not render the federation line")
        kinds = [e["type"] for e in recorder.events()]
        for expected in ("dc-join", "dc-dark", "federation-failover"):
            if expected not in kinds:
                raise Violation(f"missing flight event {expected}")

        stats.update({
            "duration_s": duration,
            "west_peers": WEST_PEERS,
            "local_p50_ms": {"pre": round(pre50 * 1e3, 3),
                             "post_dark": round(post50 * 1e3, 3)},
            "foreign_p50_ms": {
                "pre": round(_percentile(lat["foreign_pre"], .5) * 1e3, 3),
                "post_dark": round(
                    _percentile(lat["foreign_post"], .5) * 1e3, 3)},
            "failover_first_stale_ms": round(first_stale_gap * 1e3, 1),
            "convergence_recorded_ms": round(
                fed["last_convergence_seconds"] * 1e3, 1),
        })
        return stats
    finally:
        await server.stop()
        await recursion.close()
        for s in west:
            if dark_at is None:
                await s.stop()


def run_smoke(duration: float = None) -> dict:
    if duration is None:
        duration = float(os.environ.get("BINDER_FEDERATION_SECONDS", "30"))
    return asyncio.run(run_federation_incident(duration))


def main() -> int:
    try:
        stats = run_smoke()
    except Violation as e:
        print(json.dumps({"federation_smoke": "FAIL",
                          "violation": str(e)}))
        return 1
    print(json.dumps({"federation_smoke": "ok", **stats}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
