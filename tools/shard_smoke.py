#!/usr/bin/env python3
"""Shard-mode smoke: N=2 kernel-balanced workers + kill/respawn + parity.

Boots a REAL shard supervisor subprocess (``python -m binder_tpu.main
--shards 2`` on a fake-store fixture), then, while driving continuous
queries over many client sockets (distinct source ports — what makes
``SO_REUSEPORT`` actually spread load), asserts the PR's acceptance
invariants end to end:

- both workers answer (per-shard ``binder_shard_requests`` advance),
  behind ONE UDP port, from distinct PIDs;
- a ``shard-kill`` chaos fault (SIGKILL mid-load, scripted through the
  server's own chaos config block) costs no correctness: serving
  continues on the survivor, the supervisor respawns the shard
  (``binder_shard_respawns`` >= 1, new PID), and the respawn catches
  up from snapshot — post-kill mutations are served by everyone;
- the owner mirror generation is monotonic across the incident;
- answers are identical across shards (byte parity modulo ID for
  single-answer shapes, set parity for rotated service answers);
- the supervisor scrape passes ``validate_shard_metrics``;
- SIGTERM drains: the supervisor exits and leaves no orphan worker
  PIDs.

Run via ``make shard-smoke`` (30 s) or set ``BINDER_SHARD_SECONDS``.
Prints one JSON summary line; exit 0 == all invariants held.
"""
import asyncio
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.dns import Message, Rcode, Type, make_query  # noqa: E402
from tools.lint import validate_shard_metrics  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOMAIN = "shardsmoke.test"
SHARDS = 2
CLIENT_SOCKETS = 16

FIXTURE = {
    **{f"/test/shardsmoke/w{i}":
       {"type": "host", "host": {"address": f"10.40.0.{i + 1}"}}
       for i in range(8)},
    "/test/shardsmoke/svc": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 80}},
    **{f"/test/shardsmoke/svc/m{i}":
       {"type": "host", "host": {"address": f"10.40.1.{i + 1}"}}
       for i in range(3)},
}


class Violation(Exception):
    pass


def _scrape(mport: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/metrics", timeout=5) as r:
        return r.read().decode()


def _status(mport: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/status", timeout=5) as r:
        return json.loads(r.read())


def _drain_stdout(proc) -> None:
    """Keep the (non-blocking) supervisor stdout pipe empty so log
    writes from the supervisor and its workers never block on a full
    pipe mid-incident."""
    try:
        while True:
            chunk = os.read(proc.stdout.fileno(), 65536)
            if not chunk:
                return
    except (BlockingIOError, InterruptedError):
        pass
    except OSError:
        pass


def _metric(text: str, name: str, shard: int = None) -> float:
    shard_pin = '' if shard is None else 'shard="%d"' % shard
    pat = (r"^%s\{[^}]*%s[^}]*\} ([0-9.eE+-]+)$"
           % (re.escape(name), shard_pin))
    m = re.search(pat, text, re.M)
    return float(m.group(1)) if m else 0.0


async def _ask_fresh(port, name, qtype, qid, timeout=2.0) -> bytes:
    """One query on a FRESH socket (new source port -> the kernel may
    pick either shard); retries ride the same socket so a packet lost
    in a dying shard's queue costs a retry, not a hang."""
    loop = asyncio.get_running_loop()
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setblocking(False)
    sock.connect(("127.0.0.1", port))
    wire = make_query(name, qtype, qid=qid).encode()
    try:
        for _ in range(3):
            sock.send(wire)
            try:
                return await asyncio.wait_for(
                    loop.sock_recv(sock, 4096), timeout)
            except asyncio.TimeoutError:
                continue
        raise Violation("query for %s got no answer in 3 tries" % name)
    finally:
        sock.close()


async def _parity_probe(port: int, samples: int = 12) -> None:
    """Across many fresh sockets (so both shards answer), every
    single-answer shape must be byte-identical modulo the ID, and the
    rotated service answer must be the same SET of addresses."""
    for i in range(4):
        name = f"w{i}.{DOMAIN}"
        wires = set()
        for s in range(samples):
            data = await _ask_fresh(port, name, Type.A,
                                    qid=1000 + i * 64 + s)
            wires.add(b"\x00\x00" + data[2:])
        if len(wires) != 1:
            raise Violation(f"answer wires for {name} differ across "
                            f"shards ({len(wires)} variants)")
    addr_sets = set()
    for s in range(samples):
        data = await _ask_fresh(port, f"svc.{DOMAIN}", Type.A,
                                qid=2000 + s)
        msg = Message.decode(data)
        addr_sets.add(tuple(sorted(a.address for a in msg.answers)))
    if len(addr_sets) != 1:
        raise Violation(f"service answer sets differ across shards: "
                        f"{addr_sets}")


async def run_shard_incident(duration: float) -> dict:
    tmpdir = tempfile.mkdtemp(prefix="shard-smoke-")
    fixture = os.path.join(tmpdir, "fixture.json")
    config = os.path.join(tmpdir, "config.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    kill_at = max(1.5, duration * 0.35)
    storm_at = max(2.0, duration * 0.55)
    with open(config, "w") as f:
        json.dump({
            "dnsDomain": DOMAIN, "datacenterName": "dc0",
            "host": "127.0.0.1", "queryLog": False,
            "store": {"backend": "fake", "fixture": fixture},
            "shards": SHARDS,
            # the scripted incident: SIGKILL shard 0 mid-load, then a
            # mutation burst the respawned shard must also converge on
            "chaos": {"plan": f"at {kill_at:.1f} shard-kill shard=0; "
                              f"at {storm_at:.1f} watch-storm n=40"},
        }, f)
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "binder_tpu.main", "-f", config,
         "-p", "0"],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)

    stats = {"queries": 0, "ok": 0, "retries": 0}
    try:
        # wait for the supervisor's canonical announce + metrics lines
        buf = b""
        deadline = time.time() + 30
        port = mport = None
        while time.time() < deadline:
            chunk = os.read(proc.stdout.fileno(), 4096)
            if not chunk:
                raise Violation("supervisor exited during startup")
            buf += chunk
            m = re.search(rb"UDP DNS service started on "
                          rb"[\d.]+:(\d+)\"", buf)
            if m:
                port = int(m.group(1))
                mm = re.search(
                    rb"metrics server started on port (\d+)\"", buf)
                mport = int(mm.group(1)) if mm else None
                break
        if port is None or mport is None:
            raise Violation("supervisor did not report its ports")
        os.set_blocking(proc.stdout.fileno(), False)

        snap = _status(mport)
        pids0 = [w["pid"] for w in snap["shards"]["workers"]]
        if len(set(pids0)) != SHARDS:
            raise Violation(f"expected {SHARDS} distinct worker pids, "
                            f"got {pids0}")

        gen_seen = -1
        killed_pid = pids0[0]
        t_end = time.monotonic() + duration
        i = 0
        while time.monotonic() < t_end:
            i += 1
            name = f"w{i % 8}.{DOMAIN}"
            stats["queries"] += 1
            data = await _ask_fresh(port, name, Type.A,
                                    qid=(i % 0xFFFF) + 1)
            msg = Message.decode(data)
            if msg.rcode != Rcode.NOERROR or not msg.answers:
                raise Violation(f"bad answer for {name}: "
                                f"rcode {msg.rcode}")
            if msg.answers[0].address != f"10.40.0.{i % 8 + 1}":
                raise Violation(f"wrong address for {name}: "
                                f"{msg.answers[0].address}")
            stats["ok"] += 1
            if i % 29 == 0:
                _drain_stdout(proc)
                snap = _status(mport)
                gen = snap["mirror"]["generation"]
                if gen < gen_seen:
                    raise Violation(f"mirror generation regressed "
                                    f"{gen_seen} -> {gen}")
                gen_seen = gen
            await asyncio.sleep(duration / 1500.0)

        # -- post-incident assertions --
        _drain_stdout(proc)
        text = _scrape(mport)
        errs = validate_shard_metrics(text)
        if errs:
            raise Violation(f"shard metrics: {errs[:3]}")
        if _metric(text, "binder_shard_respawns", 0) < 1:
            raise Violation("killed shard was never respawned")
        snap = _status(mport)
        workers = snap["shards"]["workers"]
        if snap["shards"]["up"] != SHARDS:
            raise Violation(f"{snap['shards']['up']}/{SHARDS} shards "
                            f"up after incident")
        new_pid = workers[0]["pid"]
        if new_pid == killed_pid:
            raise Violation("shard 0 pid unchanged after SIGKILL")
        for w in workers:
            if w["requests"] <= 0:
                raise Violation(f"shard {w['shard']} answered no "
                                f"queries (reuseport never spread?)")

        # snapshot catch-up: the storm's final ring state must be
        # served by EVERY shard (fresh sockets hit both)
        final = {f"chaos{i % 8}": f"10.254.{i % 8}.{i % 250 + 1}"
                 for i in range(40)}
        for label, addr in sorted(final.items()):
            for s in range(6):
                data = await _ask_fresh(port, f"{label}.{DOMAIN}",
                                        Type.A, qid=3000 + s)
                msg = Message.decode(data)
                if not msg.answers or msg.answers[0].address != addr:
                    raise Violation(
                        f"post-respawn {label} served "
                        f"{msg.answers[0].address if msg.answers else None}"
                        f", want {addr}")
        await _parity_probe(port)

        # -- SIGTERM drain: no orphan worker PIDs --
        all_pids = [w["pid"] for w in workers]
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            raise Violation("supervisor did not exit on SIGTERM")
        deadline = time.monotonic() + 5
        orphans = list(all_pids)
        while orphans and time.monotonic() < deadline:
            orphans = [p for p in orphans if _pid_alive(p)]
            await asyncio.sleep(0.1)
        if orphans:
            raise Violation(f"orphan worker pid(s) after drain: "
                            f"{orphans}")
        stats.update({
            "duration_s": duration,
            "shards": SHARDS,
            "pids_before": pids0,
            "respawned_pid": new_pid,
            "requests_per_shard": {w["shard"]: w["requests"]
                                   for w in workers},
            "mirror_generation": gen_seen,
        })
        return stats
    finally:
        if proc.poll() is None:
            proc.kill()
        try:
            proc.wait(timeout=10)
        except Exception:
            pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def run_smoke(duration: float = None) -> dict:
    if duration is None:
        duration = float(os.environ.get("BINDER_SHARD_SECONDS", "30"))
    return asyncio.run(run_shard_incident(duration))


def main() -> int:
    try:
        stats = run_smoke()
    except Violation as e:
        print(json.dumps({"shard_smoke": "FAIL", "violation": str(e)}))
        return 1
    print(json.dumps({"shard_smoke": "ok", **stats}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
