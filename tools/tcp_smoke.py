#!/usr/bin/env python3
"""tcp-smoke: the stream lane end to end, in one scripted pass.

Boots a fake-store binder and exercises every stream-lane serving
shape the ISSUE-5 overhaul touches:

- a **one-shot** client (connect → query → read → close): the accept
  fast path must serve it and account the close
  (``binder_tcp_fast_serves`` / ``binder_tcp_oneshot_closes``);
- a **pipelined** client (two bursts on one connection): the second
  burst must promote (``binder_tcp_promotions``) and a multi-frame
  burst must coalesce into vectored writes;
- a **slow reader** against a small write-buffer cap: must be
  disconnected at the cap (``binder_tcp_slow_reader_drops``), and the
  server must keep serving others;
- a **half-close** client (send then SHUT_WR): must still receive its
  answer;
- a **torn-frame RST**: the connection table must re-converge to
  empty.

Then validates the ``binder_tcp_*`` exposition
(``tools/lint.py validate_tcp_metrics``) and the ``/status`` ``tcp``
section schema.  Prints one JSON summary line; exit 0 == all held.
Run via ``make tcp-smoke``.
"""
import asyncio
import json
import os
import socket
import struct
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.chaos.stream import (half_close,  # noqa: E402
                                     rst_mid_frame)
from binder_tpu.dns import Message, Rcode, Type, make_query  # noqa: E402
from binder_tpu.introspect import Introspector  # noqa: E402
from binder_tpu.metrics.collector import MetricsCollector  # noqa: E402
from binder_tpu.server import BinderServer  # noqa: E402
from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402
from tools.lint import (validate_status_snapshot,  # noqa: E402
                        validate_tcp_metrics)

DOMAIN = "smoke.test"


class Violation(Exception):
    pass


async def _oneshot(port, name, qid=1):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    wire = make_query(name, Type.A, qid=qid).encode()
    writer.write(struct.pack(">H", len(wire)) + wire)
    await writer.drain()
    (ln,) = struct.unpack(">H", await asyncio.wait_for(
        reader.readexactly(2), 5))
    data = await asyncio.wait_for(reader.readexactly(ln), 5)
    writer.close()
    await writer.wait_closed()
    return Message.decode(data)


async def _pipelined_bursts(port, name, per_burst=8, bursts=2):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    got = 0
    for b in range(bursts):
        block = b""
        for i in range(per_burst):
            wire = make_query(name, Type.A,
                              qid=b * per_burst + i + 1).encode()
            block += struct.pack(">H", len(wire)) + wire
        writer.write(block)
        await writer.drain()
        for _ in range(per_burst):
            (ln,) = struct.unpack(">H", await asyncio.wait_for(
                reader.readexactly(2), 5))
            msg = Message.decode(await asyncio.wait_for(
                reader.readexactly(ln), 5))
            if msg.rcode != Rcode.NOERROR:
                raise Violation(f"pipelined rcode {msg.rcode}")
            got += 1
    writer.close()
    await writer.wait_closed()
    return got


async def _slow_reader_leg(port):
    """Pump large answers without reading until the server aborts us."""
    loop = asyncio.get_running_loop()
    raw = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    raw.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    raw.setblocking(False)
    await loop.sock_connect(raw, ("127.0.0.1", port))
    wire = make_query(f"svc.{DOMAIN}", Type.A, qid=1,
                      edns_payload=4096).encode()
    frame = struct.pack(">H", len(wire)) + wire
    try:
        for i in range(20000):
            await loop.sock_sendall(raw, frame)
            if i % 64 == 0:
                await asyncio.sleep(0)
    except (ConnectionResetError, BrokenPipeError, OSError):
        return True
    finally:
        raw.close()
    return False


async def _run() -> dict:
    collector = MetricsCollector()
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/test/smoke/web",
                   {"type": "host", "host": {"address": "10.5.0.1"}})
    store.put_json("/test/smoke/svc", {
        "type": "service",
        "service": {"srvce": "_s", "proto": "_tcp", "port": 80}})
    for i in range(40):
        store.put_json(f"/test/smoke/svc/m{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.5.1.{i + 1}"}})
    store.start_session()
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="dc0", host="127.0.0.1",
                          port=0, collector=collector, query_log=False,
                          max_tcp_write_buffer=4096)
    await server.start()
    engine = server.engine
    stats = engine.tcp_stats
    try:
        # 1. one-shot (accept fast path)
        r = await _oneshot(server.tcp_port, f"web.{DOMAIN}")
        if r.rcode != Rcode.NOERROR:
            raise Violation(f"one-shot rcode {r.rcode}")
        deadline = time.monotonic() + 5.0
        while not stats.oneshot_closes and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        if not (stats.fast_serves and stats.oneshot_closes):
            raise Violation("accept fast path did not serve/close "
                            f"({stats.snapshot()})")

        # 2. pipelined bursts (promotion + coalescing)
        n = await _pipelined_bursts(server.tcp_port, f"web.{DOMAIN}")
        if n != 16:
            raise Violation(f"pipelined burst served {n}/16")
        if not stats.promotions:
            raise Violation("second burst did not promote")
        if not stats.coalesced_writes:
            raise Violation("burst responses were not coalesced")

        # 3. slow reader: disconnected at the cap
        if not await _slow_reader_leg(server.tcp_port):
            raise Violation("slow reader never disconnected")
        if not stats.slow_reader_drops:
            raise Violation("slow-reader drop not counted")
        r = await _oneshot(server.tcp_port, f"web.{DOMAIN}", qid=2)
        if r.rcode != Rcode.NOERROR:
            raise Violation("server unhealthy after slow-reader abort")

        # 4. half-close + 5. torn-frame RST (the chaos fault clients)
        await half_close("127.0.0.1", server.tcp_port, f"web.{DOMAIN}")
        await rst_mid_frame("127.0.0.1", server.tcp_port)
        deadline = time.monotonic() + 5.0
        while engine._tcp_conns and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        if engine._tcp_conns:
            raise Violation("connection table did not re-converge")

        # 6. observability gates
        errs = validate_tcp_metrics(collector.expose())
        if errs:
            raise Violation(f"tcp metrics: {errs[:3]}")
        intro = Introspector(server=server, collector=collector,
                             name="tcp-smoke")
        errs = validate_status_snapshot(intro.snapshot())
        if errs:
            raise Violation(f"status snapshot: {errs[:3]}")
        return {"tcp": stats.snapshot(),
                "cap_refusals": engine.tcp_cap_refusals}
    finally:
        await server.stop()


def main() -> int:
    try:
        stats = asyncio.run(_run())
    except Violation as e:
        print(json.dumps({"tcp_smoke": "FAIL", "violation": str(e)}))
        return 1
    print(json.dumps({"tcp_smoke": "ok", **stats}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
