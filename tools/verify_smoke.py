#!/usr/bin/env python3
"""Serving-plane verification smoke: the checker catches what it must.

Two harnesses back to back (ISSUE 16 acceptance):

**In-process** — boots a full binder (fake store + mutation-time
precompile + the verify subsystem) and runs two phases:

- *clean soak*: continuous churn + queries; the incremental checker
  and the sampled audit must evaluate real work (checks advance, audit
  passes complete, every propagation stage from ``mirror-apply`` to
  ``compiled-install`` observes) while firing ZERO violations — a
  checker that cries wolf on a healthy binder is worse than none; the
  scrape passes ``validate_verify_metrics`` and the snapshot passes
  ``validate_status_snapshot``; process RSS growth stays bounded;
- *scripted corruption*: chaos ``corrupt-answer`` and ``drop-reverse``
  (table corruption that fires NO invalidation — only the audit can
  see it), then one audit cycle.  Each corruption must be detected
  within that single cycle, and every violation must surface all three
  ways at once: ``verify-violation`` flight event, the
  ``binder_verify_violations_total{invariant}`` counter, and the
  ``recent_violations`` table in ``/status verify``.

**Subprocess** — a real N=2 shard supervisor with a scripted
``skew-replica`` fault (one delta frame suppressed to one worker, still
folded into the owner's digest roll) followed by a mutation storm: the
replica-digest invariant must flag the divergence at the next digest
frame (supervisor ``/status shards.digest_violations`` and the
``invariant="replica-digest"`` counter), serving must continue, and
SIGTERM must drain with no orphan PIDs.

Run via ``make verify-smoke`` (30 s) or set ``BINDER_VERIFY_SECONDS``.
Prints one JSON summary line; exit 0 == all invariants held.
"""
import asyncio
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.chaos import ChaosDriver, FaultPlan  # noqa: E402
from binder_tpu.dns import Message, Rcode, Type, make_query  # noqa: E402
from binder_tpu.introspect import FlightRecorder, Introspector  # noqa: E402
from binder_tpu.metrics.collector import MetricsCollector  # noqa: E402
from binder_tpu.server import BinderServer  # noqa: E402
from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402
from tools.lint import (validate_status_snapshot,  # noqa: E402
                        validate_verify_metrics)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOMAIN = "verify.test"
SKEW_DOMAIN = "verifyskew.test"
SHARDS = 2

#: in-process RSS growth bound over the whole soak+corruption run —
#: the checker/tracer reservoirs are all deque-bounded, so growth past
#: this is a leak, not workload
RSS_GROWTH_LIMIT_KB = 96 * 1024


class Violation(Exception):
    pass


def _rss_kb() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


def _invariant_counter(text: str, name: str, invariant: str) -> float:
    pat = (r'^%s\{[^}]*invariant="%s"[^}]*\} ([0-9.eE+-]+)$'
           % (re.escape(name), re.escape(invariant)))
    m = re.search(pat, text, re.M)
    return float(m.group(1)) if m else 0.0


async def _ask(port, name, qtype, qid, timeout=2.0):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(make_query(name, qtype, qid=qid).encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        return Message.decode(await asyncio.wait_for(fut, timeout))
    finally:
        transport.close()


# -- in-process: clean soak + scripted table corruption --

async def _run_inprocess(duration: float) -> dict:
    collector = MetricsCollector()
    recorder = FlightRecorder(capacity=1024)
    store = FakeStore(recorder=recorder)
    cache = MirrorCache(store, DOMAIN, collector=collector,
                        recorder=recorder)
    for i in range(8):
        store.put_json(f"/test/verify/w{i}",
                       {"type": "host",
                        "host": {"address": f"10.60.0.{i + 1}"}})
    for i in range(4):
        # churn names: each slot owns a /24 so address moves never
        # collide across names (ptr-coherence must stay clean)
        store.put_json(f"/test/verify/c{i}",
                       {"type": "host",
                        "host": {"address": f"10.60.{i + 1}.1"}})
    store.put_json("/test/verify/svc",
                   {"type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp",
                                "port": 80}})
    for i in range(3):
        store.put_json(f"/test/verify/svc/m{i}",
                       {"type": "host",
                        "host": {"address": f"10.60.9.{i + 1}"}})
    store.start_session()

    # query_log on (without the JSON log ring) stands the native tier
    # down (_fastpath_active), so every query surfaces in Python and
    # leaves re-render evidence — with the C path active, the seed
    # fills the native caches and churned names would propagate
    # mirror-apply → native-install only, never exercising the
    # precompile-render/compiled-install stages this smoke asserts
    server = BinderServer(
        zk_cache=cache, dns_domain=DOMAIN, datacenter_name="dc0",
        host="127.0.0.1", port=0, collector=collector, query_log=True,
        flight_recorder=recorder, answer_precompile=True,
        verify={"auditIntervalSeconds": 0.05})
    await server.start()
    intro = Introspector(server=server, recorder=recorder,
                         name="verify-smoke")
    intro.set_loop(asyncio.get_running_loop())
    vf = server._verify
    rss0 = _rss_kb()
    stats = {"queries": 0, "mutations": 0}
    snapshot_errs = []
    try:
        # -- phase 1: clean soak (churn + queries, zero violations) --
        loop = asyncio.get_running_loop()
        t_end = loop.time() + duration
        i = 0
        while loop.time() < t_end:
            i += 1
            store.put_json(
                f"/test/verify/c{i % 4}",
                {"type": "host",
                 "host": {"address":
                          f"10.60.{i % 4 + 1}.{i % 250 + 1}"}})
            stats["mutations"] += 1
            msg = await _ask(server.udp_port, f"w{i % 8}.{DOMAIN}",
                             Type.A, qid=(i % 0xFFFF) + 1)
            if msg.rcode != Rcode.NOERROR or not msg.answers:
                raise Violation(f"bad answer for w{i % 8}: "
                                f"rcode {msg.rcode}")
            stats["queries"] += 1
            if i % 5 == 0:
                await _ask(server.udp_port, f"c{i % 4}.{DOMAIN}",
                           Type.A, qid=20000 + i % 1000)
            if i % 7 == 0:
                await _ask(server.udp_port, f"svc.{DOMAIN}",
                           Type.A, qid=30000 + i % 1000)
            if i % 31 == 0:
                errs = validate_status_snapshot(intro.snapshot())
                if errs:
                    snapshot_errs.extend(errs)
            await asyncio.sleep(duration / 400.0)
        if snapshot_errs:
            raise Violation(f"status snapshot: {snapshot_errs[:3]}")

        fired = {k: v for k, v in vf.violations.items() if v}
        if fired:
            raise Violation(f"clean soak fired violations: {fired}")
        if not sum(vf.checks.values()):
            raise Violation("checker evaluated no invariants")
        for inv in ("ptr-coherence", "compiled-bytes", "dangling-srv",
                    "stale-epoch"):
            if not vf.checks[inv]:
                raise Violation(f"invariant {inv} never checked")
        if vf.audit_passes < 1:
            raise Violation("background audit never completed a pass")
        prop = vf.tracer.introspect()
        if not prop["observed"]:
            raise Violation("no propagation stages observed")
        for stage in ("mirror-apply", "precompile-render",
                      "compiled-install"):
            if not prop["stages"][stage]["count"]:
                raise Violation(f"propagation stage {stage} never "
                                f"observed under churn")
        errs = validate_verify_metrics(collector.expose())
        if errs:
            raise Violation(f"verify metrics: {errs[:3]}")

        # -- phase 2: scripted corruption, detected within ONE cycle --
        if not server.answer_cache._compiled:
            raise Violation("no compiled entries to corrupt")
        plan = FaultPlan(seed=3) \
            .at(0.05, "corrupt-answer") \
            .at(0.15, "drop-reverse")
        driver = ChaosDriver(plan, store=store, verify_target=server,
                             recorder=recorder)
        await driver.run()
        vf.audit_cycle()
        if vf.violations["compiled-bytes"] < 1:
            raise Violation("corrupt-answer not detected within one "
                            "audit cycle")
        if vf.violations["ptr-coherence"] < 1:
            raise Violation("drop-reverse not detected within one "
                            "audit cycle")
        # the violation -> flight event -> metrics -> /status round trip
        if recorder.by_type.get("verify-violation", 0) < 2:
            raise Violation("violations missing from the flight "
                            "recorder")
        text = collector.expose()
        for inv in ("compiled-bytes", "ptr-coherence"):
            if _invariant_counter(
                    text, "binder_verify_violations_total", inv) < 1:
                raise Violation(f"violations counter for {inv} did "
                                f"not advance")
        snap = intro.snapshot()
        recent = {v["invariant"]
                  for v in snap["verify"]["recent_violations"]}
        if not {"compiled-bytes", "ptr-coherence"} <= recent:
            raise Violation(f"/status recent_violations missing "
                            f"invariants: has {sorted(recent)}")
        errs = validate_status_snapshot(snap)
        if errs:
            raise Violation(f"status snapshot mid-violation: "
                            f"{errs[:3]}")

        growth = _rss_kb() - rss0
        if growth > RSS_GROWTH_LIMIT_KB:
            raise Violation(f"RSS grew {growth} KiB over the run "
                            f"(limit {RSS_GROWTH_LIMIT_KB})")
        stats.update({
            "checks": dict(vf.checks),
            "violations_detected": dict(vf.violations),
            "skipped": sum(vf.skipped.values()),
            "audit_passes": vf.audit_passes,
            "propagation_observed": prop["observed"],
            "rss_growth_kb": growth,
        })
        return stats
    finally:
        await server.stop()


# -- subprocess: skew-replica vs the digest frames --

SKEW_FIXTURE = {
    f"/test/verifyskew/w{i}":
    {"type": "host", "host": {"address": f"10.61.0.{i + 1}"}}
    for i in range(8)
}


async def _run_skew(duration: float) -> dict:
    from tools.shard_smoke import (_ask_fresh, _drain_stdout,
                                   _pid_alive, _scrape, _status)
    from tools.shard_smoke import Violation as ShardViolation
    tmpdir = tempfile.mkdtemp(prefix="verify-smoke-")
    fixture = os.path.join(tmpdir, "fixture.json")
    config = os.path.join(tmpdir, "config.json")
    with open(fixture, "w") as f:
        json.dump(SKEW_FIXTURE, f)
    skew_at = max(1.5, duration * 0.2)
    storm_at = skew_at + 0.8
    with open(config, "w") as f:
        json.dump({
            "dnsDomain": SKEW_DOMAIN, "datacenterName": "dc0",
            "host": "127.0.0.1", "queryLog": False,
            "store": {"backend": "fake", "fixture": fixture},
            "shards": SHARDS,
            # suppress ONE delta frame to shard 0 (still hashed into
            # the owner's roll), then a storm: the very next digest
            # frame must flag the divergence
            "chaos": {"plan":
                      f"at {skew_at:.1f} skew-replica shard=0 frames=1;"
                      f" at {storm_at:.1f} watch-storm n=20"},
        }, f)
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "binder_tpu.main", "-f", config,
         "-p", "0"],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    stats = {}
    try:
        buf = b""
        deadline = time.time() + 30
        port = mport = None
        while time.time() < deadline:
            chunk = os.read(proc.stdout.fileno(), 4096)
            if not chunk:
                raise Violation("supervisor exited during startup")
            buf += chunk
            m = re.search(rb"UDP DNS service started on "
                          rb"[\d.]+:(\d+)\"", buf)
            if m:
                port = int(m.group(1))
                mm = re.search(
                    rb"metrics server started on port (\d+)\"", buf)
                mport = int(mm.group(1)) if mm else None
                break
        if port is None or mport is None:
            raise Violation("supervisor did not report its ports")
        os.set_blocking(proc.stdout.fileno(), False)

        # the divergence must be detected before the window closes
        snap = None
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline:
            _drain_stdout(proc)
            snap = _status(mport)
            if snap["shards"]["digest_violations"] >= 1:
                break
            await asyncio.sleep(0.25)
        else:
            checks = (snap["shards"]["digest_checks"]
                      if snap is not None else None)
            raise Violation(f"replica-digest divergence never "
                            f"detected (digest checks: {checks})")
        if snap["shards"]["digest_checks"] < 1:
            raise Violation("no digest frames were ever compared")
        text = _scrape(mport)
        if _invariant_counter(text, "binder_verify_violations_total",
                              "replica-digest") < 1:
            raise Violation("replica-digest violations counter did "
                            "not advance on the supervisor scrape")

        # divergence detected, serving continues
        data = await _ask_fresh(port, f"w0.{SKEW_DOMAIN}", Type.A,
                                qid=777)
        msg = Message.decode(data)
        if msg.rcode != Rcode.NOERROR or not msg.answers:
            raise Violation("serving broke after the skew incident")

        # SIGTERM drain: no orphan worker PIDs
        pids = [w["pid"] for w in snap["shards"]["workers"]
                if w["pid"]]
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            raise Violation("supervisor did not exit on SIGTERM")
        deadline = time.monotonic() + 5
        orphans = list(pids)
        while orphans and time.monotonic() < deadline:
            orphans = [p for p in orphans if _pid_alive(p)]
            await asyncio.sleep(0.1)
        if orphans:
            raise Violation(f"orphan worker pid(s) after drain: "
                            f"{orphans}")
        stats.update({
            "digest_checks": snap["shards"]["digest_checks"],
            "digest_violations": snap["shards"]["digest_violations"],
        })
        return stats
    except ShardViolation as e:
        raise Violation(str(e))
    finally:
        if proc.poll() is None:
            proc.kill()
        try:
            proc.wait(timeout=10)
        except Exception:
            pass


def run_smoke(duration: float = None) -> dict:
    if duration is None:
        duration = float(os.environ.get("BINDER_VERIFY_SECONDS", "30"))
    stats = asyncio.run(_run_inprocess(max(3.0, duration * 0.5)))
    stats["skew_incident"] = asyncio.run(
        _run_skew(max(6.0, duration * 0.35)))
    stats["duration_s"] = duration
    return stats


def main() -> int:
    try:
        stats = run_smoke()
    except Violation as e:
        print(json.dumps({"verify_smoke": "FAIL", "violation": str(e)}))
        return 1
    print(json.dumps({"verify_smoke": "ok", **stats}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
