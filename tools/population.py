#!/usr/bin/env python3
"""Population-grade DNS traffic model (million-client realism).

``tools/hostile.py`` answers "does binder survive the open internet?"
with an *adversarial* mix — but its flows are still a few dozen
sockets, each one client.  Real authoritative traffic has a different
shape, and the RRL false-positive question is invisible without it:

- **Hundreds of thousands of distinct client identities.**  Identities
  are logical — what the server *sees* is the source address they
  query through, which is the whole point: behind a NAT'd resolver
  farm, thousands of real clients share a handful of addresses in a
  couple of /24s, so per-prefix RRL judges the farm, not the client.
  Client-side per-identity accounting (answered / refused / timeout,
  keyed by qid attribution) is what makes the collateral damage — the
  RRL false-positive rate — a measured number instead of a guess.
- **Zipf-distributed popularity.**  Both the name a query asks for and
  the identity that asks are drawn from Zipf(s) samplers: a few names
  take most of the load, a few heavy clients dominate each farm, and
  the long tail sends one query each — the distribution every cache
  and every rate limiter actually faces.
- **Realistic qtype/EDNS mixes** (A-heavy with AAAA/SRV/TXT/PTR,
  EDNS payload sizes from none to 4096) and answer-TTL observation.
- **Ramped offered load**: qps climbs linearly from a floor to a peak
  over the run, so the report shows *where* degradation starts, not
  just whether it happened at one arbitrary rate.
- **TCP retry on slip/timeout.**  A real client whose UDP query is
  dropped or answered TC=1 retries over TCP from the same source
  address.  That retry is exactly the liveness proof RRL v2's adaptive
  buckets feed on (``note_tcp``): run the same population against
  adaptive and static configs and the false-positive delta is the
  measured value of the mechanism.
- **Spoofed overlay** (optional): a concurrent spoofed-source flood
  from the SAME hostile prefixes ``tools/hostile.py`` uses, so the
  report shows RRL clamping abuse while the NAT'd farms earn their
  way out.

Synchronous selectors loop (the hostile.py discipline): the model is
the measurement instrument.  Exported JSON carries the population
shape (identities, prefixes, zipf_s, nat_fan_in) so a bench axis or a
smoke can assert against a *described* population, not a folklore one.
"""
from __future__ import annotations

import argparse
import bisect
import collections
import json
import os
import random
import selectors
import socket
import struct
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.dns.wire import make_query  # noqa: E402
from tools.hostile import (HOSTILE_PREFIXES, QTYPE_MIX,  # noqa: E402
                           _classify)

#: NAT'd resolver-farm /24s: few prefixes, high aggregate qps — the
#: cohort per-prefix RRL is most likely to false-positive on
FARM_PREFIXES = ("127.77.1", "127.77.2")

#: eyeball cohort /24s: one identity per source address, spread wide
DIRECT_PREFIXES = tuple(f"127.10.{i}" for i in range(16))

#: EDNS posture mix (payload size or None = no OPT; weights)
EDNS_MIX = ((None, 20), (512, 5), (1232, 60), (4096, 15))

DEFAULT_IDENTITIES = 200_000
DEFAULT_ZIPF_S = 1.1


class ZipfSampler:
    """Draw ranks 1..n with P(k) proportional to 1/k^s (precomputed CDF,
    O(log n) per sample)."""

    def __init__(self, n: int, s: float) -> None:
        self.n = max(1, int(n))
        self.s = float(s)
        cdf: List[float] = []
        acc = 0.0
        for k in range(1, self.n + 1):
            acc += k ** -self.s
            cdf.append(acc)
        self._cdf = cdf
        self._total = acc

    def sample(self, rng: random.Random) -> int:
        """0-based rank (0 = most popular)."""
        return bisect.bisect_left(self._cdf, rng.random() * self._total)


class Identity:
    """One logical client: the accounting unit for the FP question."""

    __slots__ = ("sent", "answered", "refused", "slipped", "timeouts",
                 "tcp_retries", "tcp_ok")

    def __init__(self) -> None:
        self.sent = 0
        self.answered = 0
        self.refused = 0
        self.slipped = 0
        self.timeouts = 0
        self.tcp_retries = 0
        self.tcp_ok = 0


class Endpoint:
    """One UDP source address (socket): what the server sees.  Farm
    endpoints carry many identities; direct endpoints exactly one."""

    __slots__ = ("sock", "src_ip", "cohort", "pending", "next_qid")

    def __init__(self, server: Tuple[str, int], src_ip: str,
                 cohort: str) -> None:
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setblocking(False)
        try:
            self.sock.bind((src_ip, 0))
        except OSError:
            self.sock.bind(("127.0.0.1", 0))   # non-Linux fallback
        self.sock.connect(server)
        self.src_ip = src_ip
        self.cohort = cohort
        #: qid -> (identity_index, name, qtype) awaiting attribution
        self.pending: Dict[int, Tuple[int, str, int]] = {}
        self.next_qid = 1

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class _TcpRetry:
    """One in-flight TCP retry from the identity's own source address
    (non-blocking connect -> length-framed query -> reply)."""

    __slots__ = ("sock", "ident", "wire", "rbuf", "deadline", "state")

    def __init__(self, server: Tuple[str, int], src_ip: str,
                 wire: bytes, ident: int, timeout: float) -> None:
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setblocking(False)
        try:
            self.sock.bind((src_ip, 0))
        except OSError:
            pass
        try:
            self.sock.connect(server)
        except BlockingIOError:
            pass
        self.ident = ident
        self.wire = struct.pack(">H", len(wire)) + wire
        self.rbuf = bytearray()
        self.deadline = time.monotonic() + timeout
        self.state = "connecting"

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def run_population(host: str, port: int, *,
                   duration: float = 10.0,
                   names: Optional[Sequence[str]] = None,
                   domain: str = "foo.com",
                   identities: int = DEFAULT_IDENTITIES,
                   farms: int = 4,
                   ips_per_farm: int = 8,
                   direct_clients: int = 48,
                   zipf_s: float = DEFAULT_ZIPF_S,
                   qps_floor: int = 300,
                   qps_peak: int = 2000,
                   spoof_share: float = 0.2,
                   reply_timeout: float = 1.0,
                   tcp_parallel: int = 16,
                   seed: int = 7) -> Dict[str, object]:
    """Drive the population model for *duration* seconds; returns the
    accounting report (see module docstring for the shape's meaning).

    ``identities`` is the NAT'd-farm population size (logical clients
    split evenly across ``farms``); ``direct_clients`` eyeballs each
    get their own source address on top.  Offered load ramps linearly
    ``qps_floor`` -> ``qps_peak``; ``spoof_share`` of sends (0..1) is
    a concurrent spoofed flood from the hostile prefixes, outside the
    legit accounting."""
    rng = random.Random(seed)
    names = list(names or [f"w{i}.{domain}" for i in range(8)])
    server = (host, port)

    # -- population layout --
    farms = max(1, int(farms))
    per_farm = max(1, int(identities) // farms)
    idents: List[Identity] = [Identity() for _ in range(per_farm * farms
                                                       + direct_clients)]
    name_zipf = ZipfSampler(len(names), zipf_s)
    ident_zipf = ZipfSampler(per_farm, zipf_s)

    endpoints: List[Endpoint] = []
    #: farm f -> its endpoints (identities behind the NAT share these)
    farm_eps: List[List[Endpoint]] = []
    for f in range(farms):
        eps = []
        for j in range(ips_per_farm):
            pfx = FARM_PREFIXES[(f * ips_per_farm + j)
                                % len(FARM_PREFIXES)]
            eps.append(Endpoint(server,
                                f"{pfx}.{(f * ips_per_farm + j) % 253 + 2}",
                                "farm"))
        farm_eps.append(eps)
        endpoints.extend(eps)
    direct_eps: List[Endpoint] = []
    for i in range(direct_clients):
        pfx = DIRECT_PREFIXES[i % len(DIRECT_PREFIXES)]
        ep = Endpoint(server, f"{pfx}.{i // len(DIRECT_PREFIXES) + 2}",
                      "direct")
        direct_eps.append(ep)
        endpoints.append(ep)
    spoof_eps: List[Endpoint] = []
    if spoof_share > 0:
        for i, pfx in enumerate(HOSTILE_PREFIXES):
            spoof_eps.append(Endpoint(server, f"{pfx}.{i + 2}", "spoof"))
    endpoints.extend(spoof_eps)

    sel = selectors.DefaultSelector()
    for ep in endpoints:
        sel.register(ep.sock, selectors.EVENT_READ, ep)

    cohorts = {c: {"sent": 0, "answered": 0, "refused": 0, "slipped": 0,
                   "timeouts": 0, "tcp_retries": 0, "tcp_ok": 0}
               for c in ("farm", "direct", "spoof")}
    ttl_seen: List[int] = [0, 0, 0]        # count, sum, max
    #: FIFO of (deadline, endpoint, qid) — reply_timeout is constant so
    #: append order IS deadline order
    expiry: collections.deque = collections.deque()
    tcp_live: List[_TcpRetry] = []
    tcp_queue: collections.deque = collections.deque()

    def account_reply(ep: Endpoint, reply: bytes) -> None:
        if len(reply) < 2:
            return
        qid = (reply[0] << 8) | reply[1]
        entry = ep.pending.pop(qid, None)
        if entry is None:
            return          # late reply past its timeout, or spoof echo
        ident_i, name, qtype = entry
        ident = idents[ident_i]
        row = cohorts[ep.cohort]
        verdict = _classify(reply)
        if verdict == "slipped":
            ident.slipped += 1
            row["slipped"] += 1
            _queue_tcp(ep.src_ip, name, qtype, ident_i)
        elif verdict == "refused":
            ident.refused += 1
            row["refused"] += 1
        else:
            ident.answered += 1
            row["answered"] += 1
            if len(reply) >= 12 and ((reply[6] << 8) | reply[7]):
                ttl = _first_ttl(reply)
                if ttl is not None:
                    ttl_seen[0] += 1
                    ttl_seen[1] += ttl
                    ttl_seen[2] = max(ttl_seen[2], ttl)

    def _queue_tcp(src_ip: str, name: str, qtype: int,
                   ident_i: int) -> None:
        ident = idents[ident_i]
        ident.tcp_retries += 1
        ep_cohort = "farm" if src_ip.rsplit(".", 1)[0] in FARM_PREFIXES \
            else "direct"
        cohorts[ep_cohort]["tcp_retries"] += 1
        wire = make_query(name, qtype, qid=(ident_i % 65535) + 1).encode()
        tcp_queue.append((src_ip, wire, ident_i))

    def pump_tcp(now: float) -> None:
        while tcp_queue and len(tcp_live) < tcp_parallel:
            src_ip, wire, ident_i = tcp_queue.popleft()
            try:
                tr = _TcpRetry(server, src_ip, wire, ident_i,
                               reply_timeout * 2)
            except OSError:
                continue
            tcp_live.append(tr)
        for tr in list(tcp_live):
            if now > tr.deadline:
                tr.close()
                tcp_live.remove(tr)
                continue
            try:
                if tr.state == "connecting":
                    try:
                        tr.sock.send(tr.wire)
                        tr.state = "sent"
                    except (BlockingIOError, InterruptedError):
                        continue
                chunk = tr.sock.recv(4096)
                if chunk:
                    tr.rbuf.extend(chunk)
                if len(tr.rbuf) >= 2:
                    (ln,) = struct.unpack_from(">H", tr.rbuf)
                    if len(tr.rbuf) >= 2 + ln:
                        reply = bytes(tr.rbuf[2:2 + ln])
                        ident = idents[tr.ident]
                        if _classify(reply) == "answered":
                            ident.tcp_ok += 1
                            row = "farm" if tr.ident < per_farm * farms \
                                else "direct"
                            cohorts[row]["tcp_ok"] += 1
                        tr.close()
                        tcp_live.remove(tr)
                elif not chunk and tr.state == "sent":
                    tr.close()
                    tcp_live.remove(tr)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                tr.close()
                tcp_live.remove(tr)

    def drain(timeout: float) -> None:
        for key, _ in sel.select(timeout):
            ep: Endpoint = key.data
            for _ in range(64):
                try:
                    reply = ep.sock.recv(65535)
                except (BlockingIOError, InterruptedError, OSError):
                    break
                account_reply(ep, reply)

    def expire(now: float) -> None:
        while expiry and expiry[0][0] <= now:
            _, ep, qid = expiry.popleft()
            entry = ep.pending.pop(qid, None)
            if entry is None:
                continue
            ident_i, name, qtype = entry
            idents[ident_i].timeouts += 1
            cohorts[ep.cohort]["timeouts"] += 1
            if ep.cohort != "spoof":
                # a real client retries a dead query over TCP — the
                # liveness proof adaptive RRL feeds on
                _queue_tcp(ep.src_ip, name, qtype, ident_i)

    def build_and_send(now: float) -> None:
        r = rng.random()
        if spoof_eps and r < spoof_share:
            ep = rng.choice(spoof_eps)
            ident_i = len(idents) - 1          # spoof rides one bucket
            cohort = "spoof"
        elif r < spoof_share + 0.15 and direct_eps:
            ep = rng.choice(direct_eps)
            ident_i = per_farm * farms + direct_eps.index(ep)
            cohort = "direct"
        else:
            f = rng.randrange(farms)
            ident_i = f * per_farm + ident_zipf.sample(rng)
            ep = rng.choice(farm_eps[f])
            cohort = "farm"
        name = names[name_zipf.sample(rng)]
        qtype = rng.choices([t for t, _ in QTYPE_MIX],
                            weights=[w for _, w in QTYPE_MIX])[0]
        payload = rng.choices([p for p, _ in EDNS_MIX],
                              weights=[w for _, w in EDNS_MIX])[0]
        qid = ep.next_qid
        ep.next_qid = (ep.next_qid % 65535) + 1
        wire = make_query(name, qtype, qid=qid,
                          edns_payload=payload).encode()
        try:
            ep.sock.send(wire)
        except OSError:
            return
        if cohort != "spoof":
            idents[ident_i].sent += 1
            ep.pending[qid] = (ident_i, name, qtype)
            expiry.append((now + reply_timeout, ep, qid))
        cohorts[cohort]["sent"] += 1

    # -- the ramped load loop --
    t0 = time.monotonic()
    deadline = t0 + duration
    credit = 0.0
    last = t0
    while True:
        now = time.monotonic()
        if now >= deadline:
            break
        frac = (now - t0) / duration
        qps = qps_floor + (qps_peak - qps_floor) * frac
        credit = min(credit + (now - last) * qps, qps * 0.05 + 32)
        last = now
        sent_this_spin = 0
        while credit >= 1.0 and sent_this_spin < 64:
            build_and_send(now)
            credit -= 1.0
            sent_this_spin += 1
        expire(now)
        pump_tcp(now)
        drain(0.0 if credit >= 1.0 else min(1.0 / max(qps, 1.0),
                                            deadline - now))
    # grace: serve out stragglers and the TCP retry tail
    grace_end = time.monotonic() + max(reply_timeout, 0.5)
    while time.monotonic() < grace_end:
        now = time.monotonic()
        drain(0.05)
        expire(now)
        pump_tcp(now)
        if not tcp_live and not tcp_queue and not expiry:
            break
    elapsed = time.monotonic() - t0

    # -- per-identity outcome distribution + FP measurement --
    active = fully = degraded = starved = 0
    farm_lost = farm_sent = 0
    n_farm_idents = per_farm * farms
    for i, ident in enumerate(idents):
        if ident.sent == 0:
            continue
        active += 1
        lost = ident.timeouts + ident.slipped - ident.tcp_ok
        lost = max(0, lost)
        if lost == 0:
            fully += 1
        elif ident.answered + ident.tcp_ok > 0:
            degraded += 1
        else:
            starved += 1
        if i < n_farm_idents:
            farm_sent += ident.sent
            farm_lost += lost
    fp_rate = round(farm_lost / farm_sent, 4) if farm_sent else 0.0

    for ep in endpoints:
        sel.unregister(ep.sock)
        ep.close()
    sel.close()
    for tr in tcp_live:
        tr.close()

    farm_row = cohorts["farm"]
    goodput = (farm_row["answered"] + farm_row["tcp_ok"]) \
        / farm_row["sent"] if farm_row["sent"] else 0.0
    return {
        "population": {
            "identities": len(idents),
            "prefixes": len(set(ep.src_ip.rsplit(".", 1)[0]
                                for ep in endpoints)),
            "zipf_s": zipf_s,
            "nat_fan_in": per_farm // max(1, ips_per_farm),
        },
        "offered": {"qps_floor": qps_floor, "qps_peak": qps_peak,
                    "duration_s": round(elapsed, 3),
                    "spoof_share": spoof_share},
        "cohorts": cohorts,
        "identity_outcomes": {"active": active, "fully_answered": fully,
                              "degraded": degraded, "starved": starved},
        "farm_goodput_ratio": round(goodput, 4),
        "rrl_false_positive_rate": fp_rate,
        "ttl_observed": {"count": ttl_seen[0],
                         "mean": round(ttl_seen[1] / ttl_seen[0], 1)
                         if ttl_seen[0] else None,
                         "max": ttl_seen[2]},
    }


def _first_ttl(reply: bytes) -> Optional[int]:
    """TTL of the first answer RR (name-skip only; best-effort)."""
    try:
        off = 12
        while reply[off]:          # skip question name
            if reply[off] & 0xC0:
                off += 1
                break
            off += reply[off] + 1
        off += 1 + 4               # null + qtype/qclass
        while reply[off]:          # skip answer owner name
            if reply[off] & 0xC0:
                off += 1
                break
            off += reply[off] + 1
        off += 1 + 4               # null/pointer tail + type/class
        return int.from_bytes(reply[off:off + 4], "big")
    except IndexError:
        return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="population-grade DNS traffic model")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--identities", type=int, default=DEFAULT_IDENTITIES)
    ap.add_argument("--farms", type=int, default=4)
    ap.add_argument("--ips-per-farm", type=int, default=8)
    ap.add_argument("--direct", type=int, default=48)
    ap.add_argument("--zipf-s", type=float, default=DEFAULT_ZIPF_S)
    ap.add_argument("--qps-floor", type=int, default=300)
    ap.add_argument("--qps-peak", type=int, default=2000)
    ap.add_argument("--spoof-share", type=float, default=0.2)
    ap.add_argument("--domain", default="foo.com")
    ap.add_argument("--names", default=None,
                    help="comma-separated realistic name population")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)
    names = args.names.split(",") if args.names else None
    report = run_population(
        args.host, args.port, duration=args.duration, names=names,
        domain=args.domain, identities=args.identities, farms=args.farms,
        ips_per_farm=args.ips_per_farm, direct_clients=args.direct,
        zipf_s=args.zipf_s, qps_floor=args.qps_floor,
        qps_peak=args.qps_peak, spoof_share=args.spoof_share,
        seed=args.seed)
    json.dump(report, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
