"""Query DNS through glibc's resolver (libresolv) and print parsed JSON.

An INDEPENDENT DNS implementation for conformance testing: glibc's
``res_query`` builds and sends the query and ``ns_initparse``/
``ns_parserr`` parse the response — none of this repo's codec is
involved on the client side (the coverage the reference got from
shelling out to dig(1), reference test/dig.js:109-134).  Uses
/etc/resolv.conf for the server address like any stub-resolver client;
run it with the conformance tier's resolv.conf override in place.

Usage: python3 tools/libresolv_probe.py NAME QTYPE
  QTYPE: A | SRV | PTR
Output: one JSON object:
  {"rcode_ok": true, "ancount": N,
   "answers": [{"name": ..., "type": N, "ttl": N, ...type fields}],
   "additional": [...same...], "opt": {"payload": N} | null}

Exit 0 on a parsed NOERROR response; 1 on lookup/parse failure (the
h_errno detail goes to stderr).
"""
import ctypes
import json
import socket
import sys

NS_MAXDNAME = 1025
C_IN = 1
QTYPES = {"A": 1, "PTR": 12, "SRV": 33}
NS_S_AN = 1     # answer section (arpa/nameser.h ns_sect)
NS_S_AR = 3     # additional section


class NsMsg(ctypes.Structure):
    # glibc arpa/nameser.h struct __ns_msg (layout stable since glibc 2.x)
    _fields_ = [
        ("_msg", ctypes.c_void_p),
        ("_eom", ctypes.c_void_p),
        ("_id", ctypes.c_uint16),
        ("_flags", ctypes.c_uint16),
        ("_counts", ctypes.c_uint16 * 4),
        ("_sections", ctypes.c_void_p * 4),
        ("_sect", ctypes.c_int),
        ("_rrnum", ctypes.c_int),
        ("_msg_ptr", ctypes.c_void_p),
    ]


class NsRr(ctypes.Structure):
    # glibc arpa/nameser.h struct __ns_rr
    _fields_ = [
        ("name", ctypes.c_char * NS_MAXDNAME),
        ("rtype", ctypes.c_uint16),
        ("rr_class", ctypes.c_uint16),
        ("ttl", ctypes.c_uint32),
        ("rdlength", ctypes.c_uint16),
        ("rdata", ctypes.c_void_p),
    ]


def main() -> int:
    name, qtype_name = sys.argv[1], sys.argv[2]
    qtype = QTYPES[qtype_name]

    res = ctypes.CDLL("libresolv.so.2", use_errno=True)
    res.res_query.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                              ctypes.c_char_p, ctypes.c_int]
    res.ns_initparse.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                 ctypes.POINTER(NsMsg)]
    res.ns_parserr.argtypes = [ctypes.POINTER(NsMsg), ctypes.c_int,
                               ctypes.c_int, ctypes.POINTER(NsRr)]
    res.ns_name_uncompress.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_char_p, ctypes.c_size_t]

    buf = ctypes.create_string_buffer(4096)
    n = res.res_query(name.encode(), C_IN, qtype, buf, len(buf))
    if n < 0:
        print("res_query failed (h_errno path)", file=sys.stderr)
        return 1

    msg = NsMsg()
    if res.ns_initparse(buf, n, ctypes.byref(msg)) != 0:
        print("ns_initparse failed", file=sys.stderr)
        return 1

    def uncompress(ptr: int) -> str:
        out = ctypes.create_string_buffer(NS_MAXDNAME)
        got = res.ns_name_uncompress(msg._msg, msg._eom, ptr, out,
                                     NS_MAXDNAME)
        if got < 0:
            raise ValueError("ns_name_uncompress failed")
        return out.value.decode()

    def parse_section(sect: int, count: int):
        records = []
        opt = None
        for i in range(count):
            rr = NsRr()
            if res.ns_parserr(ctypes.byref(msg), sect, i,
                              ctypes.byref(rr)) != 0:
                raise ValueError(f"ns_parserr failed ({sect},{i})")
            rd = ctypes.string_at(rr.rdata, rr.rdlength) \
                if rr.rdlength else b""
            rec = {"name": rr.name.decode(), "type": rr.rtype,
                   "ttl": rr.ttl}
            if rr.rtype == 41:          # OPT: class carries the payload
                opt = {"payload": rr.rr_class}
                continue
            if rr.rtype == 1 and len(rd) == 4:
                rec["address"] = socket.inet_ntoa(rd)
            elif rr.rtype == 33 and len(rd) >= 6:
                rec["priority"] = (rd[0] << 8) | rd[1]
                rec["weight"] = (rd[2] << 8) | rd[3]
                rec["port"] = (rd[4] << 8) | rd[5]
                rec["target"] = uncompress(rr.rdata + 6)
            elif rr.rtype == 12:
                rec["target"] = uncompress(rr.rdata)
            records.append(rec)
        return records, opt

    answers, _ = parse_section(NS_S_AN, msg._counts[NS_S_AN])
    additional, opt = parse_section(NS_S_AR, msg._counts[NS_S_AR])
    print(json.dumps({
        "rcode_ok": True,               # res_query returns <0 otherwise
        "ancount": msg._counts[NS_S_AN],
        "answers": answers,
        "additional": additional,
        "opt": opt,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
