#!/usr/bin/env python3
"""population-smoke: million-client realism as a CI gate.

Two phases, each against a REAL ``binder_tpu.main`` subprocess:

**Phase A — population vs RRL v2 (single process).**  Runs the
population model (``tools/population.py``: Zipf name/identity
popularity, NAT'd resolver farms concentrated in two /24s, a spoofed
overlay, ramped offered load, TCP retry on slip/timeout) against a
server with deliberately low RRL limits, ``adaptive: true``, and the
eyeball cohort's /16 allowlisted.  Asserts:

- **goodput floor**: the NAT'd farm cohort's end-to-end goodput
  (UDP answers + TCP-retry recoveries over sent) stays above the
  smoke floor even though the farm prefixes ARE rate-limited;
- **FP ceiling**: the measured RRL false-positive rate (legit farm
  queries lost and never recovered) stays under the ceiling — the
  adaptive buckets' whole job;
- **adaptation engaged**: ``binder_rrl_adaptations_total`` >= 1 (the
  farms' TCP retries earned a bigger bucket) while the spoofed
  overlay still shows drops (``binder_rrl_dropped_total`` > 0);
- **allowlist honored**: ``binder_rrl_allowlisted_total`` > 0 and the
  exposition passes the extended ``validate_rrl_metrics``.

**Phase B — zero-downtime rolling operations (2-shard supervisor).**
Mid-incident (a scripted ``rrl-flood`` burst), the chaos DSL's
``worker-roll`` rolls every shard; once ``rolls_total`` reaches 2 the
smoke sends SIGHUP (the config-reload entry point) to roll them all
again.  A closed-loop allowlisted probe runs across both rolls.
Asserts:

- **zero query loss**: no probe query is ever lost (and first-try
  timeouts stay within a freak-packet tolerance) across 4 rolls;
- **drain-and-replace end to end**: every worker PID changed, twice;
  ``binder_shard_rolls_total`` == 2 per shard, zero aborts; workers
  logged "quiesced clean" (in-flight served out before exit); shard
  0's promotion completed before shard 1's replacement spawned (rolls
  are sequential by construction);
- the supervisor scrape passes the extended
  ``validate_shard_metrics`` (roll counters present from scrape 1).

``BINDER_POPULATION_SECONDS`` overrides the total budget (default 30;
``make ci`` trims to 10).  Prints one JSON summary line; exit 0 ==
all held.  Run via ``make population-smoke``.
"""
import json
import os
import re
import select
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from binder_tpu.dns.wire import Type, make_query  # noqa: E402
from tools.population import run_population  # noqa: E402
from tools.lint import (validate_rrl_metrics,  # noqa: E402
                        validate_shard_metrics,
                        validate_status_snapshot)

DOMAIN = "popsmoke.test"
DURATION = float(os.environ.get("BINDER_POPULATION_SECONDS", "30"))
SHARDS = 2
#: low enough that the farm /24s trip RRL fast, high enough that one
#: adaptation step visibly relieves them
RRL_RPS, RRL_BURST = 60, 120
#: smoke floors/ceilings (the bench's population axis records the real
#: numbers; the gate only refuses regressions to "RRL starves farms")
GOODPUT_FLOOR = 0.5
FP_CEILING = 0.10
#: freak-packet tolerance for first-try probe timeouts across 4 rolls
#: (the quiesce drain leaves a sub-millisecond close window); LOST
#: queries get zero tolerance
ROLL_RETRY_TOLERANCE = 3


class Violation(Exception):
    pass


def _write_config(tmpdir, *, shards=None, chaos=None, allowlist=()):
    fixture = {f"/test/popsmoke/w{i}":
               {"type": "host", "host": {"address": f"10.77.0.{i + 1}"}}
               for i in range(16)}
    fixture_path = os.path.join(tmpdir, "fixture.json")
    with open(fixture_path, "w") as f:
        json.dump(fixture, f)
    cfg = {
        "dnsDomain": DOMAIN, "datacenterName": "dc0",
        "host": "127.0.0.1", "queryLog": False,
        "store": {"backend": "fake", "fixture": fixture_path},
        "rrl": {"responsesPerSecond": RRL_RPS, "burst": RRL_BURST,
                "slipRatio": 2, "maxBuckets": 512,
                "adaptive": True, "adaptEvidence": 3,
                "allowlist": list(allowlist)},
    }
    if shards:
        cfg["shards"] = shards
    if chaos:
        cfg["chaos"] = chaos
    config_path = os.path.join(tmpdir, "config.json")
    with open(config_path, "w") as f:
        json.dump(cfg, f)
    return config_path


def _boot(config):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "binder_tpu.main", "-f", config,
         "-p", "0"],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    buf = b""
    deadline = time.time() + 30
    while time.time() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(0.0, deadline - time.time()))
        if not ready:
            break
        chunk = os.read(proc.stdout.fileno(), 65536)
        if not chunk:
            raise Violation("server exited during startup")
        buf += chunk
        m = re.search(rb"UDP DNS service started on [\d.]+:(\d+)\"", buf)
        mm = re.search(rb"metrics server started on port (\d+)\"", buf)
        if m and mm:
            os.set_blocking(proc.stdout.fileno(), False)
            return proc, int(m.group(1)), int(mm.group(1)), buf
    raise Violation("server did not report its ports in time")


def _drain_stdout(proc, buf):
    try:
        while True:
            chunk = os.read(proc.stdout.fileno(), 65536)
            if not chunk:
                return buf
            buf += chunk
    except (BlockingIOError, InterruptedError, OSError):
        pass
    return buf


def _scrape(mport, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}{path}", timeout=5) as r:
        return r.read().decode()


def _metric(text, name):
    total = 0.0
    for m in re.finditer(rf"^{name}(?:{{[^}}]*}})? ([0-9.eE+-]+)$",
                         text, re.M):
        total += float(m.group(1))
    return total


def _stop(proc):
    if proc is None:
        return
    try:
        proc.terminate()
        proc.wait(timeout=10)
    except Exception:
        try:
            proc.kill()
            proc.wait(timeout=10)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Phase A


def phase_population(duration: float) -> dict:
    tmpdir = tempfile.mkdtemp(prefix="pop_smoke_a_")
    # allowlist the DIRECT (eyeball) cohort's /16: those sources skip
    # RRL pre-decode; the farm prefixes are deliberately NOT listed —
    # they must earn relief through the adaptive path
    config = _write_config(tmpdir, allowlist=("127.10.0.0/16",))
    proc = None
    try:
        proc, port, mport, _ = _boot(config)
        report = run_population(
            "127.0.0.1", port, duration=duration, domain=DOMAIN,
            names=[f"w{i}.{DOMAIN}" for i in range(16)],
            identities=100_000, qps_floor=300, qps_peak=1500,
            spoof_share=0.2)
        if proc.poll() is not None:
            raise Violation("server died under population load")

        goodput = report["farm_goodput_ratio"]
        if goodput < GOODPUT_FLOOR:
            raise Violation(f"farm goodput {goodput} under floor "
                            f"{GOODPUT_FLOOR}")
        fp = report["rrl_false_positive_rate"]
        if fp > FP_CEILING:
            raise Violation(f"RRL false-positive rate {fp} over "
                            f"ceiling {FP_CEILING}")

        text = _scrape(mport, "/metrics")
        errs = validate_rrl_metrics(text)
        if errs:
            raise Violation(f"rrl metrics: {errs[:3]}")
        if _metric(text, "binder_rrl_dropped_total") <= 0:
            raise Violation("spoof overlay was never dropped")
        if _metric(text, "binder_rrl_adaptations_total") < 1:
            raise Violation("adaptive buckets never engaged (no "
                            "TCP-retry evidence consumed)")
        if _metric(text, "binder_rrl_allowlisted_total") <= 0:
            raise Violation("allowlisted eyeball cohort never counted")
        status = json.loads(_scrape(mport, "/status"))
        errs = validate_status_snapshot(status)
        if errs:
            raise Violation(f"status snapshot: {errs[:3]}")
        rrl_status = (status.get("policy") or {}).get("rrl") or {}
        return {
            "population": report["population"],
            "farm_goodput_ratio": goodput,
            "rrl_false_positive_rate": fp,
            "identity_outcomes": report["identity_outcomes"],
            "cohorts": {c: row["sent"]
                        for c, row in report["cohorts"].items()},
            "rrl": {
                "dropped": _metric(text, "binder_rrl_dropped_total"),
                "adaptations": _metric(text,
                                       "binder_rrl_adaptations_total"),
                "adapted_buckets": _metric(text,
                                           "binder_rrl_adapted_buckets"),
                "allowlisted": _metric(text,
                                       "binder_rrl_allowlisted_total"),
                "false_positives": _metric(
                    text, "binder_rrl_false_positives_total"),
                "status_adapted": rrl_status.get("adapted_buckets"),
            },
        }
    finally:
        _stop(proc)


# ---------------------------------------------------------------------------
# Phase B


def _probe_once(port, qid, timeout=1.5):
    """One closed-loop query; returns tries used (1..3) or raises."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.connect(("127.0.0.1", port))
    sock.settimeout(timeout)
    wire = make_query(f"w{qid % 16}.{DOMAIN}", Type.A,
                      qid=(qid % 65535) + 1).encode()
    try:
        for attempt in range(1, 4):
            sock.send(wire)
            try:
                reply = sock.recv(65535)
            except socket.timeout:
                continue
            if len(reply) >= 12 and (reply[3] & 0xF) == 0:
                return attempt
        return 0      # lost entirely
    finally:
        sock.close()


def phase_rolling(duration: float) -> dict:
    tmpdir = tempfile.mkdtemp(prefix="pop_smoke_b_")
    flood_at = max(1.0, duration * 0.15)
    roll_at = max(1.5, duration * 0.25)
    config = _write_config(
        tmpdir, shards=SHARDS,
        # the mid-incident script: a spoofed burst trips RRL, then the
        # DSL's worker-roll drains-and-replaces every shard under it
        chaos={"plan": f"at {flood_at:.1f} rrl-flood n=400; "
                       f"at {roll_at:.1f} worker-roll"},
        allowlist=("127.0.0.0/24",))
    proc = None
    try:
        proc, port, mport, buf = _boot(config)
        status = json.loads(_scrape(mport, "/status"))
        pids0 = [w["pid"] for w in status["shards"]["workers"]]
        if len(set(pids0)) != SHARDS:
            raise Violation(f"expected {SHARDS} worker pids, {pids0}")

        stats = {"queries": 0, "retried": 0, "lost": 0}
        sighup_sent = False
        pids1 = []
        deadline = time.monotonic() + duration + 25.0
        i = 0
        while time.monotonic() < deadline:
            i += 1
            tries = _probe_once(port, i)
            stats["queries"] += 1
            if tries == 0:
                stats["lost"] += 1
            elif tries > 1:
                stats["retried"] += 1
            if i % 10 == 0:
                buf = _drain_stdout(proc, buf)
                snap = json.loads(_scrape(mport, "/status"))
                rolls = snap["shards"]["rolls_total"]
                if rolls >= SHARDS and not sighup_sent:
                    # chaos roll done: exercise the config-reload
                    # entry point on the same live group
                    pids1 = [w["pid"]
                             for w in snap["shards"]["workers"]]
                    proc.send_signal(signal.SIGHUP)
                    sighup_sent = True
                elif rolls >= 2 * SHARDS:
                    break
            time.sleep(max(0.005, duration / 400.0))
        buf = _drain_stdout(proc, buf)

        snap = json.loads(_scrape(mport, "/status"))
        sh = snap["shards"]
        if sh["rolls_total"] < 2 * SHARDS:
            raise Violation(f"only {sh['rolls_total']} rolls completed "
                            f"(want {2 * SHARDS}: chaos + SIGHUP)")
        if sh["roll_aborts"]:
            raise Violation(f"{sh['roll_aborts']} roll step(s) aborted")
        pids2 = [w["pid"] for w in sh["workers"]]
        if set(pids2) & set(pids0) or (pids1 and set(pids2) & set(pids1)):
            raise Violation(f"worker pids survived a roll: "
                            f"{pids0} -> {pids1} -> {pids2}")
        if stats["lost"]:
            raise Violation(f"{stats['lost']} probe quer(ies) lost "
                            f"across {sh['rolls_total']} rolls")
        if stats["retried"] > ROLL_RETRY_TOLERANCE:
            raise Violation(f"{stats['retried']} probe retries across "
                            f"rolls (tolerance {ROLL_RETRY_TOLERANCE})")

        # drain-and-replace evidence, from the workers' own mouths:
        # every drained incumbent served out its in-flight before exit
        quiesced = buf.count(b"quiesced clean")
        if quiesced < 2 * SHARDS:
            raise Violation(f"only {quiesced} clean quiesces logged "
                            f"(want {2 * SHARDS})")
        # sequential rolls: shard 0's cycle completed before shard 1's
        # replacement was even spawned
        first_done = buf.find(b"shard 0 rolled: pid")
        second_spawn = buf.find(b"shard 1 replacement spawned")
        if first_done == -1 or second_spawn == -1 \
                or second_spawn < first_done:
            raise Violation("rolls were not sequential (shard 1 "
                            "replacement before shard 0 promotion)")

        text = _scrape(mport, "/metrics")
        errs = validate_shard_metrics(text)
        if errs:
            raise Violation(f"shard metrics: {errs[:3]}")
        if _metric(text, "binder_shard_rolls_total") < 2 * SHARDS:
            raise Violation("binder_shard_rolls_total under-counts")

        # the flood engaged RRL inside at least one worker (folded
        # rrl drops surface in the supervisor's shard aggregates)
        if _metric(text, "binder_shard_rrl_dropped") <= 0:
            raise Violation("rrl-flood never engaged the workers' RRL")

        stats.update({
            "rolls_total": sh["rolls_total"],
            "roll_aborts": sh["roll_aborts"],
            "pids": {"boot": pids0, "after_chaos_roll": pids1,
                     "after_sighup_roll": pids2},
            "quiesced_clean": quiesced,
        })
        return stats
    finally:
        _stop(proc)


def main() -> int:
    try:
        a = phase_population(max(5.0, DURATION * 0.5))
        b = phase_rolling(max(6.0, DURATION * 0.5))
    except Violation as e:
        print(json.dumps({"population_smoke": "FAIL",
                          "violation": str(e)}))
        return 1
    print(json.dumps({"population_smoke": "ok", "duration_s": DURATION,
                      "population": a, "rolling": b}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
