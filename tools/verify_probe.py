#!/usr/bin/env python3
"""Verify-plane probe: mutation→glass latency + checker cost at N names.

The measurement half of ISSUE 16's ``verify`` bench axis, run as one
subprocess per zone size (like tools/zone_probe.py, whose answer-path
harness it reuses) so the sizes never pollute each other's RSS.

Builds a synthetic zone, wires the zone_probe Harness (mirror →
invalidate → precompile, the BinderServer answer path minus
transports), and measures:

- a control mutation burst with NO verifier wired: the baseline
  single-name mutation latency (p50/p99) at this zone size;
- the same burst with the full verify plane wired — propagation
  tracer on the mirror + precompiler, incremental checker fed by the
  per-name invalidation tags (no event loop, so the checker drains
  INLINE and its entire cost lands in the measured latency — the
  honest worst case; in the server it amortizes across loop passes);
- the per-stage mutation→glass propagation figures off the tracer
  itself (`mirror-apply` / `precompile-render` / `compiled-install`;
  every figure end-to-end from the store event, exactly what
  `binder_propagation_seconds` records in production) — the
  O(delta) claim is these staying flat from 10k to 1M names;
- one full background-audit pass: wall time, slice count, the worst
  single slice (the loop-stall bound — budget is 2 ms), checks by
  invariant, and the violation count, which must be ZERO on an
  uncorrupted zone at any size.

Usage:  python tools/verify_probe.py <names> [mutations] [sample]
Prints one JSON line.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402
from binder_tpu.store.fake import populate_synthetic  # noqa: E402
from binder_tpu.verify import Verifier  # noqa: E402
from tools.zone_probe import (  # noqa: E402
    DOMAIN, Harness, host_name, host_path)


def _pcts(lat_us):
    lat_us = sorted(lat_us)
    return (round(lat_us[len(lat_us) // 2], 1),
            round(lat_us[min(len(lat_us) - 1,
                             int(len(lat_us) * 0.99))], 1))


def probe(n: int, mutations: int = 400, sample: int = 0) -> dict:
    racks = max(1, min(1024, n // 512))
    if sample <= 0:
        # full-coverage pass at small sizes; at zone scale sample the
        # audit the way production would (residue rotation still
        # covers everything across `sample` passes)
        sample = 1 if n <= 20000 else 8
    out = {"names": n, "audit_sample": sample}

    store = FakeStore()
    populate_synthetic(store, DOMAIN, n, racks=racks)
    cache = MirrorCache(store, DOMAIN)
    store.start_session()
    h = Harness(cache)

    step = max(1, n // max(1, mutations))
    idx = list(range(0, n, step))[:mutations]
    for i in idx:
        h.prime(host_name(i, racks))

    def burst(octet: int):
        lat = []
        for j, i in enumerate(idx):
            body = json.dumps(
                {"type": "host",
                 "host": {"address":
                          f"10.{octet}.{(j >> 8) & 255}.{j & 255}"}}
            ).encode()
            t0 = time.perf_counter()
            store.set_data(host_path(i, racks), body)
            lat.append((time.perf_counter() - t0) * 1e6)
        return lat

    # control: the bare mirror → invalidate → re-render chain
    p50, p99 = _pcts(burst(210))
    out["mutation_p50_us"] = p50
    out["mutation_p99_us"] = p99
    out["mutation_samples"] = len(idx)

    # wire the verify plane the way BinderServer does (server.py):
    # tracer on the mirror (store-event stamp + mirror-apply) and on
    # the precompiler (render/install stages), checker fed by the
    # same invalidation tags the answer cache drops
    vf = Verifier(zk_cache=cache, answer_cache=h.answer_cache,
                  resolver=h.resolver, precompiler=h.pc,
                  config={"auditSample": sample})
    cache.tracer = vf.tracer
    h.pc.tracer = vf.tracer
    cache.on_invalidate(vf.enqueue_tags)

    p50v, p99v = _pcts(burst(211))
    out["mutation_checked_p50_us"] = p50v
    out["mutation_checked_p99_us"] = p99v
    out["mutation_checked_vs_control"] = round(
        p50v / p50, 3) if p50 else None

    tr = vf.tracer.introspect()
    out["propagation"] = {
        stage: {"count": s["count"],
                "p50_us": round(s["p50_seconds"] * 1e6, 1),
                "p99_us": round(s["p99_seconds"] * 1e6, 1)}
        for stage, s in tr["stages"].items() if s["count"]}

    # one full audit pass, slice by slice, worst slice recorded (the
    # production audit runs exactly these slices off a loop timer —
    # the worst slice IS the stall it can inject)
    worst = 0.0
    slices = 0
    t0 = time.perf_counter()
    vf.audit_slice()
    slices += 1
    while vf._audit_work:
        s0 = time.perf_counter()
        vf.audit_slice()
        worst = max(worst, time.perf_counter() - s0)
        slices += 1
    out["audit_wall_s"] = round(time.perf_counter() - t0, 3)
    out["audit_slices"] = slices
    out["audit_worst_slice_ms"] = round(worst * 1000, 3)
    snap = vf.introspect()
    out["checks"] = {k: v for k, v in snap["checks"].items() if v}
    out["violations"] = sum(snap["violations"].values())
    out["skipped"] = {k: v for k, v in snap["skipped"].items() if v}
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    n = int(argv[0]) if argv else 10000
    mutations = int(argv[1]) if len(argv) > 1 else 400
    sample = int(argv[2]) if len(argv) > 2 else 0
    print(json.dumps(probe(n, mutations, sample)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
