#!/usr/bin/env python3
"""Scripted-FaultPlan smoke: the degradation policy engine, end to end.

Boots a full in-process binder (fake store + recursion to a chaos
upstream + degradation/admission policy), runs a scripted FaultPlan —
upstream packet loss, ZK session loss mid-churn, a watch storm,
misbehaving stream clients (slow reader / half-close / torn-frame
RST), an event-loop stall, then recovery — while driving continuous
queries, and asserts the PR's acceptance invariants:

- every query gets a well-formed answer or refusal (never a hang);
- data answers are served only while fresh or within
  ``maxStalenessSeconds`` (stale answers TTL-clamped);
- past the cap answers are withheld (SERVFAIL), never stale;
- after the faults heal, the system re-converges: mirror generation
  advances, ``binder_degraded_state`` returns to 0, breakers close;
- the scrape passes ``validate_degradation_metrics`` and the status
  snapshot passes ``validate_status_snapshot`` mid-incident.

Run via ``make chaos-smoke`` (30 s) or set ``BINDER_CHAOS_SECONDS``.
Prints one JSON summary line; exit 0 == all invariants held.
"""
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.chaos import ChaosDriver, ChaosUpstream, FaultPlan  # noqa: E402
from binder_tpu.dns import Message, Rcode, Type, make_query  # noqa: E402
from binder_tpu.introspect import FlightRecorder, Introspector  # noqa: E402
from binder_tpu.metrics.collector import MetricsCollector  # noqa: E402
from binder_tpu.recursion import Recursion, StaticResolverSource  # noqa: E402
from binder_tpu.recursion.client import DnsClient  # noqa: E402
from binder_tpu.server import BinderServer  # noqa: E402
from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402
from tools.lint import (validate_degradation_metrics,  # noqa: E402
                        validate_status_snapshot)

DOMAIN = "chaos.test"


class Violation(Exception):
    pass


async def _ask(port, name, qtype, qid, rd=False, timeout=1.0):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(make_query(name, qtype, qid=qid,
                                        rd=rd).encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        return Message.decode(await asyncio.wait_for(fut, timeout))
    finally:
        transport.close()


async def _run(duration: float) -> dict:
    collector = MetricsCollector()
    recorder = FlightRecorder(capacity=1024)
    store = FakeStore(recorder=recorder)
    cache = MirrorCache(store, DOMAIN, collector=collector,
                        recorder=recorder)
    for i in range(8):
        store.put_json(f"/test/chaos/w{i}",
                       {"type": "host",
                        "host": {"address": f"10.0.2.{i + 1}"}})
    store.start_session()

    up_plan = FaultPlan(seed=11)
    upstream = ChaosUpstream(up_plan,
                             hosts={f"w.remote.{DOMAIN}": "10.9.9.9"})
    up_port = await upstream.start()
    recursion = Recursion(
        zk_cache=cache, dns_domain=DOMAIN, datacenter_name="dc0",
        source=StaticResolverSource({"remote": [f"127.0.0.1:{up_port}"]}),
        nic_provider=lambda: [],
        client=DnsClient(timeout=0.25),
        collector=collector, recorder=recorder)
    await recursion.wait_ready()

    # floored: at the short durations the test harness uses, a purely
    # proportional cap makes the fresh->stale->exhausted windows so
    # narrow that scheduler jitter alone can skip a mode entirely
    max_staleness = max(0.6, duration * 0.08)
    server = BinderServer(
        zk_cache=cache, dns_domain=DOMAIN, datacenter_name="dc0",
        host="127.0.0.1", port=0, collector=collector, query_log=False,
        flight_recorder=recorder, recursion=recursion,
        degradation={"maxStalenessSeconds": max_staleness,
                     "staleTtlClampSeconds": 5},
        admission={"maxInflight": 128},
        # RRL v2 mid-incident posture: the measurement client's /24 is
        # allowlisted (pre-decode, never limited) so the scripted
        # rrl-flood clamps ONLY the attacker prefixes while every
        # invariant below keeps being asserted through the flood
        rrl={"responsesPerSecond": 20, "burst": 40,
             "allowlist": ["127.0.0.0/24"]})
    await server.start()
    intro = Introspector(server=server, recorder=recorder,
                         collector=collector, name="chaos-smoke")
    intro.set_loop(asyncio.get_running_loop())

    plan = FaultPlan(seed=7) \
        .at(duration * 0.10, "upstream", loss=0.4) \
        .at(duration * 0.20, "lose-session") \
        .at(duration * 0.25, "watch-storm", n=100) \
        .at(duration * 0.30, "tcp-slow-reader", conns=1, queries=64,
            hold_ms=200) \
        .at(duration * 0.35, "tcp-half-close", queries=2) \
        .at(duration * 0.40, "tcp-rst", conns=2) \
        .at(duration * 0.45, "loop-stall", ms=120) \
        .at(duration * 0.50, "rrl-flood", n=400) \
        .at(duration * 0.65, "restore-session") \
        .at(duration * 0.70, "upstream", clear=True)
    plan.upstream = up_plan.upstream   # faults act on the live upstream

    def mutate(i):
        store.put_json(f"/test/chaos/churn{i % 4}",
                       {"type": "host",
                        "host": {"address": f"10.7.0.{i % 200 + 1}"}})

    driver = ChaosDriver(plan, store=store, mutate=mutate,
                         tcp_target=("127.0.0.1", server.tcp_port,
                                     f"w0.{DOMAIN}"),
                         udp_target=("127.0.0.1", server.udp_port,
                                     f"w0.{DOMAIN}"),
                         recorder=recorder)
    chaos_task = driver.start()

    pol = server._policy
    stats = {"queries": 0, "ok": 0, "stale": 0, "refused": 0,
             "servfail": 0, "rd_timeouts": 0}
    snapshot_errs = []
    t_end = asyncio.get_running_loop().time() + duration
    i = 0
    try:
        while asyncio.get_running_loop().time() < t_end:
            i += 1
            rd = i % 5 == 0
            name = (f"w.remote.{DOMAIN}" if rd
                    else f"w{i % 8}.{DOMAIN}")
            stats["queries"] += 1
            try:
                msg = await _ask(server.udp_port, name, Type.A,
                                 qid=(i % 0xFFFF) + 1, rd=rd)
            except asyncio.TimeoutError:
                if not rd:
                    raise Violation(f"local query for {name} hung")
                stats["rd_timeouts"] += 1
                continue
            mode = pol.mode()
            if msg.rcode == Rcode.NOERROR and msg.answers:
                if mode == "stale-exhausted" and not rd:
                    raise Violation("data served while stale-exhausted")
                ds = store.disconnected_seconds()
                if ds is not None and not rd \
                        and ds > max_staleness + 1.0:
                    raise Violation(
                        f"answer served {ds:.2f}s stale "
                        f"(cap {max_staleness:.2f}s)")
                if mode == "stale-serving" and not rd:
                    if any(a.ttl > 5 for a in msg.answers):
                        raise Violation("stale answer TTL not clamped")
                    stats["stale"] += 1
                stats["ok"] += 1
            elif msg.rcode == Rcode.REFUSED:
                stats["refused"] += 1
            elif msg.rcode == Rcode.SERVFAIL:
                stats["servfail"] += 1
            else:
                raise Violation(f"unexpected rcode {msg.rcode}")
            if i % 37 == 0:
                errs = validate_status_snapshot(intro.snapshot())
                if errs:
                    snapshot_errs.extend(errs)
            await asyncio.sleep(duration / 600.0)

        await asyncio.wait_for(chaos_task, duration)
        if snapshot_errs:
            raise Violation(f"status snapshot: {snapshot_errs[:3]}")
        if not stats["stale"]:
            raise Violation("stale-serving window never observed")
        if not stats["servfail"]:
            raise Violation("stale-exhausted window never observed")

        # -- re-convergence --
        gen_before = cache.gen
        store.put_json("/test/chaos/w0",
                       {"type": "host", "host": {"address": "10.0.2.99"}})
        if cache.gen <= gen_before:
            raise Violation("mirror generation did not advance")
        deadline = time.monotonic() + 5.0
        while pol.mode() != "fresh" and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if pol.mode() != "fresh":
            raise Violation("degraded state did not return to fresh")
        if collector.get("binder_degraded_state").value() != 0.0:
            raise Violation("binder_degraded_state != 0 after recovery")
        msg = await _ask(server.udp_port, f"w0.{DOMAIN}", Type.A,
                         qid=9999)
        if msg.rcode != Rcode.NOERROR \
                or msg.answers[0].address != "10.0.2.99":
            raise Violation("post-recovery answer wrong")
        if recursion.breakers.open_count():
            raise Violation("breakers still open after recovery")
        # stream-lane re-convergence: the misbehaving TCP clients
        # (slow reader, half-close, torn-frame RST) were all shed and
        # the connection table is empty again
        await driver.stream_quiesce()
        deadline = time.monotonic() + 5.0
        while server.engine._tcp_conns and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if server.engine._tcp_conns:
            raise Violation("TCP connection table did not re-converge")
        tcp_stats = server.engine.tcp_stats
        if not tcp_stats.accepts:
            raise Violation("stream faults never reached the listener")
        errs = validate_degradation_metrics(collector.expose())
        if errs:
            raise Violation(f"degradation metrics: {errs[:3]}")
        # rrl-flood engagement: the spoofed burst must have been
        # limited (dropped or slipped), and the measurement client's
        # allowlisted /24 must have bypassed RRL entirely — the flood
        # ran mid-incident, so the invariants above already prove
        # serving survived it
        rrl = server._rrl
        if rrl.dropped + rrl.slipped == 0:
            raise Violation("rrl-flood was never rate-limited")
        if rrl.allowlisted == 0:
            raise Violation("allowlisted measurement prefix never "
                            "bypassed RRL")
        stats["rrl"] = {"dropped": rrl.dropped, "slipped": rrl.slipped,
                        "allowlisted": rrl.allowlisted}
        stats["tcp"] = tcp_stats.snapshot()
        stats["flight_events"] = dict(recorder.by_type)
        stats["shed"] = dict(server._admission.shed_counts)
        stats["stale_served_total"] = pol.stale_served
        stats["withheld_total"] = pol.withheld
        stats["duration_s"] = duration
        return stats
    finally:
        await server.stop()
        await recursion.close()
        await upstream.stop()


def run_smoke(duration: float = None) -> dict:
    if duration is None:
        duration = float(os.environ.get("BINDER_CHAOS_SECONDS", "30"))
    return asyncio.run(_run(duration))


def main() -> int:
    try:
        stats = run_smoke()
    except Violation as e:
        print(json.dumps({"chaos_smoke": "FAIL", "violation": str(e)}))
        return 1
    # shard-kill incident (ISSUE 6): the scripted multi-process phase —
    # a SIGKILLed worker mid-load must cost nothing observable beyond a
    # respawn (re-converged serving, monotonic mirror generation);
    # tools/shard_smoke.py owns the harness, this wires it into the
    # chaos gate with a proportionally short window
    from tools.shard_smoke import Violation as ShardViolation
    from tools.shard_smoke import run_shard_incident
    duration = float(os.environ.get("BINDER_CHAOS_SECONDS", "30"))
    try:
        shard_stats = asyncio.run(
            run_shard_incident(max(6.0, duration * 0.4)))
    except ShardViolation as e:
        print(json.dumps({"chaos_smoke": "FAIL",
                          "violation": f"shard incident: {e}"}))
        return 1
    stats["shard_incident"] = {
        k: shard_stats[k] for k in ("queries", "ok", "respawned_pid",
                                    "requests_per_shard")}
    print(json.dumps({"chaos_smoke": "ok", **stats}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
