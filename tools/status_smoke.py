#!/usr/bin/env python3
"""status-smoke: boot a fake-store binder, fetch /status, validate, exit.

The CI-sized proof that the introspection layer works end to end over
real HTTP: a server on an ephemeral port with the fake store, one
resolved query (so the snapshot carries non-trivial cache state), a
scrape-thread fetch of ``/status``, the snapshot-schema validator from
``tools/lint.py``, and a ``/metrics`` fetch through the Prometheus
exposition validator (the introspection gauges must not break the
scrape).  Exit 0 == both validators clean.  Run via `make status-smoke`.
"""
import asyncio
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.introspect import (FlightRecorder, Introspector,  # noqa: E402
                                   LoopLagWatchdog)
from binder_tpu.metrics.collector import (MetricsCollector,  # noqa: E402
                                          MetricsServer)
from binder_tpu.server import BinderServer  # noqa: E402
from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402
from tools.lint import (validate_exposition,  # noqa: E402
                        validate_status_snapshot)

DOMAIN = "foo.com"


async def run() -> int:
    recorder = FlightRecorder()
    collector = MetricsCollector()
    store = FakeStore(recorder=recorder)
    cache = MirrorCache(store, DOMAIN, collector=collector,
                        recorder=recorder)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "10.0.0.1"}})
    store.start_session()

    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="dc0", host="127.0.0.1",
                          port=0, collector=collector, query_log=False,
                          flight_recorder=recorder)
    await server.start()
    watchdog = LoopLagWatchdog(collector=collector, recorder=recorder,
                               interval=0.02)
    watchdog.start()
    intro = Introspector(server=server, recorder=recorder,
                         watchdog=watchdog, collector=collector)
    intro.set_loop(asyncio.get_running_loop())
    metrics = MetricsServer(collector, address="127.0.0.1", port=0)
    metrics.status_source = intro.snapshot
    metrics.start()

    # one real query so the snapshot reflects serve-path state
    from binder_tpu.dns import Type, make_query
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(make_query(f"web.{DOMAIN}", Type.A,
                                        qid=7).encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", server.udp_port))
    await asyncio.wait_for(fut, 5)
    transport.close()
    await asyncio.sleep(0.1)   # a couple of watchdog samples

    rc = 0
    url = f"http://127.0.0.1:{metrics.port}"
    snap = await asyncio.to_thread(lambda: json.loads(
        urllib.request.urlopen(f"{url}/status", timeout=5).read()))
    errs = validate_status_snapshot(snap)
    for e in errs:
        print(f"status-smoke: snapshot: {e}", file=sys.stderr)
    rc |= 1 if errs else 0

    text = await asyncio.to_thread(lambda: urllib.request.urlopen(
        f"{url}/metrics", timeout=5).read().decode())
    for metric in ("binder_zk_session_state", "binder_loop_lag_seconds",
                   "binder_mirror_staleness_seconds",
                   "binder_inflight_queries"):
        if metric not in text:
            print(f"status-smoke: scrape missing {metric}",
                  file=sys.stderr)
            rc |= 1
    errs = validate_exposition(text)
    for e in errs:
        print(f"status-smoke: exposition: {e}", file=sys.stderr)
    rc |= 1 if errs else 0

    watchdog.stop()
    await server.stop()
    metrics.stop()
    if rc == 0:
        print(f"status-smoke: ok (store={snap['store']['state']}, "
              f"mirror nodes={snap['mirror']['nodes']}, "
              f"loop samples={snap['loop']['samples']})")
    return rc


def main() -> int:
    return asyncio.run(run())


if __name__ == "__main__":
    sys.exit(main())
