#!/usr/bin/env python3
"""hostile-smoke: the hostile-traffic plane end to end, as a CI gate.

Boots a real server process (``binder_tpu.main`` with a fake-store
fixture and a deliberately low RRL limit), measures a no-flood legit
goodput control, then runs the adversarial multi-flow harness
(``tools/hostile.py``) against it — spoofed-source flood from hostile
prefixes, malformed/EDNS/oversized frames, cache-missing random names,
realistic queries — while the same paced legit client measures goodput
*under* the flood.  Asserts the hostile-internet invariants:

- **RRL engaged**: the spoof prefixes see slips (TC=1 echoes) and
  silent drops; ``binder_rrl_dropped_total`` and
  ``binder_shed_total{reason="response-ratelimit"}`` moved.
- **Legit goodput survives**: the paced 127.0.0.1 client (its own
  /24, under the per-prefix limit) keeps a goodput ratio vs the
  no-flood control above the smoke floor.  The bench's ``hostile``
  axis records the real number; this gate only refuses regressions
  to "flood starves everyone".
- **Fuzz-clean**: malformed frames produce FORMERR-or-drop (never a
  served answer), and the server process stays up throughout.
- **Bounded state**: server RSS growth over the soak stays bounded
  (the RRL bucket LRU + prefix cache must not grow with source
  diversity), and ``binder_rrl_buckets`` respects ``maxBuckets``.
- **Observability**: the ``binder_rrl_*`` exposition validates
  (``tools/lint.py validate_rrl_metrics``) and ``/status`` carries
  the ``policy.rrl`` section.

``BINDER_HOSTILE_SECONDS`` overrides the flood duration (default 30;
``make ci`` trims to 10).  Prints one JSON summary line; exit 0 ==
all held.  Run via ``make hostile-smoke``.
"""
import json
import os
import re
import select
import subprocess
import sys
import tempfile
import time
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.hostile import legit_probe  # noqa: E402
from tools.lint import (validate_rrl_metrics,  # noqa: E402
                        validate_status_snapshot)

DOMAIN = "smoke.test"
DURATION = float(os.environ.get("BINDER_HOSTILE_SECONDS", "30"))
#: paced legit offered load — must sit under RRL_RPS (see below) so
#: the probe measures the flood's collateral damage, not its own shed
LEGIT_QPS = 100
#: RRL config for the smoke server: low enough that the spoof flood
#: (hundreds-to-thousands of rps per hostile /24) trips it within the
#: first second, high enough that the paced legit client never does
RRL_RPS, RRL_BURST, RRL_MAX_BUCKETS = 150, 300, 512
#: flood pacing: the smoke asserts the *policy* sheds the flood, so
#: the offered load is paced to what one Python server keeps up with —
#: kernel socket-buffer overflow shedding legit traffic alongside the
#: flood would measure capacity, not the limiter
FLOOD_QPS = 6000
FLOOD_FLOWS = 64
#: RSS growth bound over the soak; the bucket LRU (512 entries) and
#: prefix cache are the only per-flood state, orders of magnitude less
MAX_RSS_GROWTH_KB = 64 * 1024
#: smoke floor for goodput-under-flood vs control (the bench axis
#: records the real ratio; ISSUE 12's target there is >= 0.8)
GOODPUT_FLOOR = 0.5


class Violation(Exception):
    pass


def _write_configs(tmpdir):
    fixture = {f"/test/smoke/w{i}":
               {"type": "host", "host": {"address": f"10.9.0.{i + 1}"}}
               for i in range(8)}
    fixture_path = os.path.join(tmpdir, "fixture.json")
    with open(fixture_path, "w") as f:
        json.dump(fixture, f)
    config_path = os.path.join(tmpdir, "config.json")
    with open(config_path, "w") as f:
        json.dump({
            "dnsDomain": DOMAIN, "datacenterName": "dc0",
            "host": "127.0.0.1",
            "store": {"backend": "fake", "fixture": fixture_path},
            "queryLog": False,
            "rrl": {"responsesPerSecond": RRL_RPS, "burst": RRL_BURST,
                    "slipRatio": 2, "maxBuckets": RRL_MAX_BUCKETS},
        }, f)
    return config_path


def _wait_for_ports(proc, timeout=30.0):
    deadline = time.time() + timeout
    buf = b""
    while time.time() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(0.0, deadline - time.time()))
        if not ready:
            break
        chunk = os.read(proc.stdout.fileno(), 4096)
        if not chunk:
            raise Violation("server exited during startup")
        buf += chunk
        m = re.search(rb"UDP DNS service started on [\d.]+:(\d+)\"", buf)
        if m:
            mm = re.search(rb"metrics server started on port (\d+)\"", buf)
            if mm is None:
                raise Violation("server did not report a metrics port")
            return int(m.group(1)), int(mm.group(1))
    raise Violation("server did not report its port in time")


def _rss_kb(pid):
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return None


def _scrape(mport, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}{path}", timeout=5) as r:
        return r.read().decode()


def _metric(text, name):
    total = 0.0
    for m in re.finditer(rf"^{name}(?:{{[^}}]*}})? ([0-9.eE+-]+)$",
                         text, re.M):
        total += float(m.group(1))
    return total


def _run():
    tmpdir = tempfile.mkdtemp(prefix="hostile_smoke_")
    config = _write_configs(tmpdir)
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    server = subprocess.Popen(
        [sys.executable, "-u", "-m", "binder_tpu.main", "-f", config,
         "-p", "0"],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    flood = None
    try:
        port, mport = _wait_for_ports(server)

        # 1. no-flood control: paced legit goodput
        control = legit_probe("127.0.0.1", port,
                              duration=max(2.0, DURATION * 0.1),
                              domain=DOMAIN, qps=LEGIT_QPS)
        if not control["answered"]:
            raise Violation(f"control probe got no answers ({control})")

        rss_before = _rss_kb(server.pid)

        # 2. the flood (separate process: the harness must not share
        # the probe's GIL) + the same paced probe under it
        flood = subprocess.Popen(
            [sys.executable, "-u",
             os.path.join(ROOT, "tools", "hostile.py"),
             "--port", str(port), "--duration", str(DURATION),
             "--flows", str(FLOOD_FLOWS), "--qps", str(FLOOD_QPS),
             "--domain", DOMAIN],
            cwd=ROOT, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        time.sleep(0.5)   # let the flood trip the limiter first
        under = legit_probe("127.0.0.1", port,
                            duration=max(1.0, DURATION - 1.5),
                            domain=DOMAIN, qps=LEGIT_QPS)
        out, _ = flood.communicate(timeout=DURATION + 30)
        if flood.returncode != 0:
            raise Violation(f"hostile harness exited {flood.returncode}")
        report = json.loads(out)

        if server.poll() is not None:
            raise Violation("server died under hostile load")
        rss_after = _rss_kb(server.pid)

        # 3. RRL engaged: the spoof prefixes got slipped/dropped
        spoof = report["categories"]["spoof"]
        if not (spoof["slipped"] or spoof["dropped"]):
            raise Violation(f"spoof flood was never rate-limited ({spoof})")
        if not spoof["slipped"]:
            raise Violation("no TC=1 slips observed (slipRatio=2 config)")

        # 4. fuzz-clean: malformed traffic is FORMERR-or-drop, never
        # a served answer (tiny tolerance for qid-collision
        # misattribution across categories sharing a flow)
        malformed = report["categories"]["malformed"]
        if malformed["sent"] and (malformed["answered"]
                                  > 0.02 * malformed["sent"] + 3):
            raise Violation(f"malformed frames got answers ({malformed})")

        # 5. legit goodput under flood vs control
        ratio = (under["qps"] / control["qps"]) if control["qps"] else 0.0
        if ratio < GOODPUT_FLOOR:
            raise Violation(
                f"legit goodput collapsed under flood: {under['qps']} "
                f"vs control {control['qps']} qps (ratio {ratio:.2f})")

        # 6. bounded state: RSS growth and the bucket cap
        if (rss_before is not None and rss_after is not None
                and rss_after - rss_before > MAX_RSS_GROWTH_KB):
            raise Violation(f"server RSS grew {rss_after - rss_before} kB "
                            f"over the soak (cap {MAX_RSS_GROWTH_KB})")

        # 7. observability: exposition + /status schema + shed series
        text = _scrape(mport, "/metrics")
        errs = validate_rrl_metrics(text)
        if errs:
            raise Violation(f"rrl metrics: {errs[:3]}")
        if _metric(text, "binder_rrl_dropped_total") <= 0:
            raise Violation("binder_rrl_dropped_total never moved")
        if _metric(text, "binder_rrl_buckets") > RRL_MAX_BUCKETS:
            raise Violation("binder_rrl_buckets exceeds maxBuckets")
        status = json.loads(_scrape(mport, "/status"))
        errs = validate_status_snapshot(status)
        if errs:
            raise Violation(f"status snapshot: {errs[:3]}")
        rrl_status = (status.get("policy") or {}).get("rrl")
        if not rrl_status or not rrl_status.get("dropped"):
            raise Violation(f"/status policy.rrl missing or idle "
                            f"({rrl_status})")

        # 8. post-flood health: the server answers normally again
        after = legit_probe("127.0.0.1", port, duration=1.0,
                            domain=DOMAIN, qps=50)
        if not after["answered"]:
            raise Violation("server unhealthy after the flood")

        return {
            "duration_s": DURATION,
            "control_qps": control["qps"],
            "under_flood_qps": under["qps"],
            "goodput_ratio": round(ratio, 3),
            "under_flood": under,
            "hostile_qps": report["hostile_qps"],
            "flows": report["flows"],
            "spoof": spoof,
            "malformed": malformed,
            "rss_growth_kb": (rss_after - rss_before
                              if rss_before and rss_after else None),
            "rrl": {"dropped": _metric(text, "binder_rrl_dropped_total"),
                    "slipped": _metric(text, "binder_rrl_slipped_total"),
                    "responses": _metric(text,
                                         "binder_rrl_responses_total"),
                    "buckets": _metric(text, "binder_rrl_buckets")},
        }
    finally:
        for proc in (flood, server):
            if proc is None:
                continue
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except Exception:
                try:
                    proc.kill()
                    proc.wait(timeout=10)
                except Exception:
                    pass


def main() -> int:
    try:
        stats = _run()
    except Violation as e:
        print(json.dumps({"hostile_smoke": "FAIL", "violation": str(e)}))
        return 1
    print(json.dumps({"hostile_smoke": "ok", **stats}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
