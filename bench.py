#!/usr/bin/env python3
"""Benchmark: DNS queries/sec + p50 resolve latency through the full stack.

This is the BASELINE.md proxy metric — the reference publishes no numbers
(BASELINE.json: "published": {}), so ``vs_baseline`` compares against the
first locally measured value, persisted to ``BENCH_BASELINE.json``.

Prints exactly ONE JSON line:
    {"metric": "dns_queries_per_sec", "logged_qps": N, "value": M,
     "unit": "qps", "vs_baseline": R, "p50_us": ..., "p99_us": ...}

``logged_qps`` leads: it is the REFERENCE-PARITY headline — the
reference logs every query unconditionally, so the always-logging
posture is the comparable number; ``value`` (the log-off hit path) is
the hardware ceiling it is judged against (``logged_vs_headline``).
Axes that front other subsystems carry per-stage ``*_attribution``
blocks (docs/observability.md) so a cross-round delta names its owning
stage instead of being bisected blind.

Scenario (mirrors the reference's test/service.test.js hot path, SURVEY §3.2):
a service record with multiple load-balancer children, resolved as
round-robin A answers plus SRV lookups, via the in-process resolution engine
over the fake coordination store — i.e. the same pure in-memory hot loop the
reference serves from its ZK mirror.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    try:
        from bench_impl import run_bench  # full-stack benchmark (added with the stack)
        result = run_bench()
    except Exception as e:  # stack not built yet / failed — report honestly
        result = {
            "metric": "dns_queries_per_sec",
            "value": 0,
            "unit": "qps",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }
    print(json.dumps(result))
    # a broken bench must fail the `make ci` bench-smoke gate, not just
    # report an error field (the driver reads the JSON either way)
    return 1 if "error" in result else 0


if __name__ == "__main__":
    sys.exit(main())
