# Top-level targets mirroring the reference's Makefile surface
# (`make test` / `make check`, reference Makefile:169-171 + Jenkinsfile).

PY ?= python3

.PHONY: all native test check bench clean

all: native

native:
	$(MAKE) -C native

test: native
	$(PY) -m pytest tests/ -q

# style/consistency gate (the reference's `make check` runs jsstyle/jsl;
# here: byte-compile everything, keep the native build warning-clean
# (-B: a stale object must not mask a warning), and smoke the
# sanitizer-built fuzzers over the native parsers)
check:
	$(PY) -m compileall -q binder_tpu tests bench.py bench_impl.py \
		__graft_entry__.py
	$(MAKE) -B -C native \
		CXXFLAGS="-O2 -g -Wall -Wextra -Werror -std=c++17" \
		CFLAGS="-O2 -g -Wall -Wextra -Werror"
	$(MAKE) -C native fuzz-smoke

bench: native
	$(PY) bench.py

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
