# Top-level targets mirroring the reference's Makefile surface
# (`make test` / `make check`, reference Makefile:169-171 + Jenkinsfile).

PY ?= python3

.PHONY: all native test check ci bench bench-smoke clean

all: native

native:
	$(MAKE) -C native

test: native
	$(PY) -m pytest tests/ -q

# style/consistency gate (the reference's `make check` runs the vendored
# jsstyle/javascriptlint, reference Jenkinsfile:37-40; here: byte-compile
# everything, a first-party zero-warning Python lint (tools/lint.py),
# keep the native build warning-clean (-B: a stale object must not mask
# a warning), smoke the sanitizer-built fuzzers over the native parsers,
# and run the fastio pytest suites against the ASan-built extension)
check:
	$(PY) -m compileall -q binder_tpu tests bench.py bench_impl.py \
		__graft_entry__.py
	$(PY) tools/lint.py
	$(MAKE) -B -C native \
		CXXFLAGS="-O2 -g -Wall -Wextra -Werror -std=c++17" \
		CFLAGS="-O2 -g -Wall -Wextra -Werror"
	$(MAKE) -C native fuzz-smoke
	$(MAKE) -C native check-asan

# the reference's Jenkins pipeline as one invocable unit
# (Jenkinsfile:25-41: checkout -> check -> [test]); extended with the
# gates the reference leaves to production: full test suite + bench
# smoke.  Explicitly sequential: check's ASan extension swap must not
# race test's pytest import under `make -j`.
ci:
	$(MAKE) check
	$(MAKE) test
	$(MAKE) bench-smoke
	@echo "ci: all gates passed"

# one fast reduced-iteration bench pass proving the measured paths still
# run end to end (its numbers are not comparable: small samples, and the
# baseline write is diverted); the driver runs the full bench.py separately
bench-smoke: native
	@mkdir -p .scratch
	BENCH_QUERIES=5000 BENCH_PASSES=1 BENCH_MISS_QUERIES=2000 \
		BENCH_BASELINE_FILE=.scratch/bench_smoke_baseline.json \
		$(PY) bench.py

bench: native
	$(PY) bench.py

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
