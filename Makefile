# Top-level targets mirroring the reference's Makefile surface
# (`make test` / `make check`, reference Makefile:169-171 + Jenkinsfile).

PY ?= python3

.PHONY: all native test check ci bench bench-smoke status-smoke \
	chaos-smoke tcp-smoke shard-smoke zone-smoke federation-smoke \
	hostile-smoke verify-smoke balancer-smoke population-smoke \
	real-tiers clean

all: native

native:
	$(MAKE) -C native

# after the suite, name every conformance tier with ran/skip + reason —
# a silently skipped tier must be visible in the round log
CONFORMANCE_STRICT ?=
test: native
	@mkdir -p .scratch
	$(PY) -m pytest tests/ -q --junitxml=.scratch/junit.xml
	$(PY) tools/conformance_tiers.py .scratch/junit.xml $(CONFORMANCE_STRICT)

# style/consistency gate (the reference's `make check` runs the vendored
# jsstyle/javascriptlint, reference Jenkinsfile:37-40; here: byte-compile
# everything, a first-party zero-warning Python lint (tools/lint.py),
# keep the native build warning-clean (-B: a stale object must not mask
# a warning), smoke the sanitizer-built fuzzers over the native parsers,
# and run the fastio pytest suites against the ASan-built extension)
check:
	$(PY) -m compileall -q binder_tpu tests bench.py bench_impl.py \
		__graft_entry__.py
	$(PY) tools/lint.py
	$(MAKE) -B -C native \
		CXXFLAGS="-O2 -g -Wall -Wextra -Werror -std=c++17" \
		CFLAGS="-O2 -g -Wall -Wextra -Werror"
	$(MAKE) -C native fuzz-smoke
	$(MAKE) -C native check-asan

# the reference's Jenkins pipeline as one invocable unit
# (Jenkinsfile:25-41: checkout -> check -> [test]); extended with the
# gates the reference leaves to production: full test suite + bench
# smoke.  Explicitly sequential: check's ASan extension swap must not
# race test's pytest import under `make -j`.
# ci turns the glibc stub-resolver tier on when running as root (it
# rewrites /etc/resolv.conf and binds 127.0.0.1:53, so plain `make
# test` keeps it opt-in) and then requires that at least one
# independent DNS client actually executed (--strict).
# BINDER_LIBC_CONFORMANCE=0 runs ci without the host mutation and
# visibly waives the independence gate (informed opt-out).
ci:
	$(MAKE) check
	$(MAKE) test CONFORMANCE_STRICT=--strict \
		BINDER_LIBC_CONFORMANCE="$${BINDER_LIBC_CONFORMANCE-$$([ "$$(id -u)" = 0 ] && echo 1)}"
	$(MAKE) bench-smoke
	BINDER_CHAOS_SECONDS=10 $(MAKE) chaos-smoke
	$(MAKE) tcp-smoke
	BINDER_SHARD_SECONDS=10 $(MAKE) shard-smoke
	BINDER_ZONE_NAMES=20000 $(MAKE) zone-smoke
	BINDER_FEDERATION_SECONDS=10 $(MAKE) federation-smoke
	BINDER_HOSTILE_SECONDS=10 $(MAKE) hostile-smoke
	BINDER_VERIFY_SECONDS=10 $(MAKE) verify-smoke
	BINDER_BALANCER_SECONDS=10 $(MAKE) balancer-smoke
	BINDER_POPULATION_SECONDS=10 $(MAKE) population-smoke
	@echo "ci: all gates passed"

# one fast reduced-iteration bench pass proving the measured paths still
# run end to end (its numbers are not comparable: small samples, and the
# baseline write is diverted); the driver runs the full bench.py separately
bench-smoke: native
	@mkdir -p .scratch
	BENCH_QUERIES=5000 BENCH_PASSES=1 BENCH_MISS_QUERIES=2000 \
		BENCH_RECURSION_QUERIES=2000 BENCH_TCP1_QUERIES=1500 \
		BENCH_TC_FLOWS=300 BENCH_SHARD_NS=1,2 \
		BENCH_POPULATION_SECONDS=8 \
		BENCH_BASELINE_FILE=.scratch/bench_smoke_baseline.json \
		$(PY) bench.py

bench: native
	$(PY) bench.py

# introspection end-to-end smoke: boot a fake-store server, fetch the
# /status snapshot over HTTP, run the snapshot-schema and Prometheus
# exposition validators, exit (docs/observability.md)
status-smoke:
	$(PY) tools/status_smoke.py

# degradation end-to-end smoke: 30 s scripted FaultPlan (upstream
# packet loss, ZK session loss mid-churn, watch storm, loop stall,
# recovery) against a live in-process binder, asserting the
# correct-or-refused / never-staler-than-cap / re-converges invariants
# (docs/degradation.md); BINDER_CHAOS_SECONDS overrides the duration
# (tier-1 runs the same harness short via tests/test_chaos.py)
chaos-smoke:
	$(PY) tools/chaos_smoke.py

# shard-mode end-to-end smoke: 30 s N=2 supervisor (real worker
# processes on one SO_REUSEPORT port), scripted shard-kill mid-load,
# respawn + snapshot catch-up, cross-shard answer parity, SIGTERM
# drain with no orphan PIDs, binder_shard_* exposition validation
# (docs/operations.md "Sharded serving"); BINDER_SHARD_SECONDS
# overrides the duration
shard-smoke:
	$(PY) tools/shard_smoke.py

# zone-scale smoke: build a synthetic 100k-name mirror (control: 2k),
# apply a mutation burst + watch storm through the real mirror ->
# invalidate -> precompile chain, and assert the million-name
# representation's invariants: single-name rebuild latency independent
# of zone size (O(delta)), re-rendered answers byte-identical to fresh
# engine renders, chunked session rebuild under the loop-lag watchdog
# threshold with serving continuing throughout, and the
# binder_mirror_* exposition pins (docs/operations.md "Large zones");
# BINDER_ZONE_NAMES overrides the size (make ci trims to 20k)
zone-smoke:
	$(PY) tools/zone_smoke.py

# federation end-to-end smoke: two in-process DC groups over real
# loopback UDP, scripted whole-DC loss mid-load — local names stay
# line-rate, cached foreign names serve stale (TTL-clamped NOERROR),
# uncached ones get a well-formed REFUSED, zero client-visible
# timeouts; plus binder_federation_* exposition, /status + bstat
# federation sections, and the failover flight events
# (docs/federation.md); BINDER_FEDERATION_SECONDS overrides the
# duration (make ci trims to 10 s)
federation-smoke:
	$(PY) tools/federation_smoke.py

# stream-lane end-to-end smoke: one-shot (accept fast path), pipelined
# promotion + write coalescing, slow-reader disconnect at the
# write-buffer cap, half-close, torn-frame RST, then the binder_tcp_*
# exposition and /status tcp-section validators (docs/operations.md)
tcp-smoke:
	$(PY) tools/tcp_smoke.py

# hostile-traffic end-to-end smoke: a real server process under the
# adversarial multi-flow harness (tools/hostile.py) — spoofed-source
# flood from hostile prefixes, malformed/EDNS/oversized frames —
# asserting RRL slips/drops engage, paced legit goodput survives,
# malformed traffic is FORMERR-or-drop, RSS stays bounded, and the
# binder_rrl_* exposition + /status policy.rrl validate
# (docs/operations.md "Binder is under attack");
# BINDER_HOSTILE_SECONDS overrides the flood duration (ci trims to 10)
hostile-smoke:
	$(PY) tools/hostile_smoke.py

# balancer-fronted end-to-end smoke: real mbalancer + two backends,
# direct-return negotiation (fd passing), continuous fronted load with
# a mid-stream backend kill + revival — zero client-visible timeouts,
# affinity re-pointed, direct return renegotiated on re-adoption, and
# the stats-socket stage/batch counters monotone across the churn
# (docs/balancer-protocol.md); BINDER_BALANCER_SECONDS overrides the
# duration (make ci trims to 10 s)
balancer-smoke:
	$(PY) tools/balancer_smoke.py

# million-client realism smoke: the Zipf/NAT'd-farm population model
# vs RRL v2 (goodput floor, measured false-positive ceiling, adaptive
# buckets + allowlist engaged), then a 2-shard rolling drain-and-
# replace under a scripted rrl-flood — chaos worker-roll AND SIGHUP
# config-reload, zero probe-query loss (docs/operations.md);
# BINDER_POPULATION_SECONDS overrides the budget (make ci trims to 10)
population-smoke:
	$(PY) tools/population_smoke.py

# serving-plane verification smoke: clean soak (zero violations while
# the checker, audit and propagation tracer all do real work, RSS
# bounded), then scripted chaos corruptions (corrupt-answer,
# drop-reverse) each detected within ONE audit cycle and surfaced as
# flight event + metric + /status, then a real N=2 supervisor with a
# skew-replica fault caught by the replica-digest frames
# (docs/observability.md); BINDER_VERIFY_SECONDS overrides the
# duration (make ci trims to 10 s)
verify-smoke:
	$(PY) tools/verify_smoke.py

# Both real-infrastructure conformance tiers in one command, with the
# session transcript written into docs/ (VERDICT r5 item 8): the moment
# either tier becomes runnable on a capable box, the evidence lands
# next to docs/real-tier-status.md with zero friction.  Environment
# knobs are the tiers' own: ZK_HOST/ZK_PORT for the real-ZooKeeper
# tier, BINDER_SYSTEMD_CONFORMANCE=1 (root on a systemd-PID-1 host)
# for the real-systemd tier — unset, each suite reports its skip
# reason into the log, which is itself the honest record.  Runs both
# suites even if the first fails; exits non-zero if either failed.
REAL_TIER_LOG = docs/real-tier-session.log
real-tiers:
	@{ echo "# real-tier conformance session"; \
	   echo "date: $$(date -u +%Y-%m-%dT%H:%M:%SZ)"; \
	   echo "host: $$(uname -srmo) ($$(hostname))"; \
	   echo "commit: $$(git rev-parse --short HEAD 2>/dev/null || echo '?')"; \
	   echo "ZK_HOST=$${ZK_HOST-<unset>} ZK_PORT=$${ZK_PORT-<unset>} " \
	        "BINDER_SYSTEMD_CONFORMANCE=$${BINDER_SYSTEMD_CONFORMANCE-<unset>}"; \
	   echo; } | tee $(REAL_TIER_LOG)
	@rc=0; \
	echo "== real-zookeeper tier ==" | tee -a $(REAL_TIER_LOG); \
	$(PY) -m pytest tests/test_conformance.py::TestRealZooKeeper -v -rs \
	    2>&1 | tee -a $(REAL_TIER_LOG) || rc=1; \
	echo "== real-systemd tier ==" | tee -a $(REAL_TIER_LOG); \
	$(PY) -m pytest tests/test_systemd_real_conformance.py -v -rs \
	    2>&1 | tee -a $(REAL_TIER_LOG) || rc=1; \
	echo "session log: $(REAL_TIER_LOG)"; exit $$rc

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
