"""Full-stack DNS benchmark (invoked by bench.py).

Measures the BASELINE.md proxy metric — DNS queries/sec and resolve-latency
percentiles — against a REAL binder server process (`python -m
binder_tpu.main`) over loopback UDP, dnsperf-style: the load generator
keeps a window of queries in flight and only parses the response id +
rcode, so the measurement is server capacity, not client parsing.

Query mix mirrors BASELINE.json's proxy configs: single-host A lookups,
round-robin service A lookups, SRV lookups, and PTR lookups.  The server
runs with queryLog disabled (per-query JSON logging is an ops knob;
latency histograms still observe every query — the reference's bunyan
per-query logging would equally dominate any single-machine benchmark).
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import select
import shutil
import signal
import socket as _socket_mod
import subprocess
import sys
import tempfile
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from binder_tpu.dns import Type, make_query

ROOT = os.path.dirname(os.path.abspath(__file__))

# ---------------------------------------------------------------------------
# Core pinning (VERDICT r3 item 1): on a multi-core box the server stack
# and the load generator share cores by scheduler whim, which is exactly
# the noise that made r2->r3 driver numbers uninterpretable.  With >=2
# cores, pin the serving processes (binder, balancer, zk) to the first
# half and the load drivers to the second half so every pass measures
# the same contention topology.  Single-core boxes run unpinned (there
# is nothing to separate) and say so in the env fingerprint.

# the ALLOWED set, not os.cpu_count(): in a cpuset-restricted container
# the machine may have 64 cores while this process is allowed {4,5} —
# taskset onto disallowed IDs would kill every pinned child at launch
try:
    _CORES = sorted(os.sched_getaffinity(0))
except (AttributeError, OSError):
    _CORES = list(range(os.cpu_count() or 1))
NPROC = len(_CORES)
TASKSET = shutil.which("taskset")
PINNED = bool(TASKSET) and NPROC >= 2 and \
    os.environ.get("BENCH_PIN", "1") != "0"
_SPLIT = NPROC // 2
SERVER_CORES = ",".join(str(c) for c in _CORES[:_SPLIT]) or "0"
CLIENT_CORES = ",".join(str(c) for c in _CORES[_SPLIT:]) or "0"


def _pin(role: str) -> List[str]:
    """argv prefix pinning `role` ('server'|'client') to its core set."""
    if not PINNED:
        return []
    return [TASKSET, "-c",
            SERVER_CORES if role == "server" else CLIENT_CORES]


def _env_fingerprint() -> Dict[str, object]:
    """Recorded in the bench JSON so cross-round/cross-box numbers are
    interpretable (VERDICT r3: 'records nothing about the environment,
    so cross-round driver numbers are uninterpretable')."""
    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    try:
        load1 = round(os.getloadavg()[0], 2)
    except OSError:
        load1 = None
    # ALWAYS record the allowed-CPU set and machine core count, pinned
    # or not: an unpinned run previously wrote nulls here, making
    # scaling/efficiency numbers unreadable against the actual CPU
    # topology (which is exactly what the shard axis divides by)
    all_cores = ",".join(str(c) for c in _CORES)
    return {"cpu": model, "cores": NPROC,
            "affinity": all_cores,
            "nproc_machine": os.cpu_count(),
            "pinned": PINNED,
            "server_cores": SERVER_CORES if PINNED else all_cores,
            "client_cores": CLIENT_CORES if PINNED else all_cores,
            "loadavg_start": load1, "passes": N_PASSES,
            # zone size every standard axis is measured at (ISSUE 7:
            # a qps figure without its zone scale is uninterpretable;
            # the zone_scale axis carries its own per-size blocks)
            "zone": _fixture_zone()}


def _fixture_zone() -> Dict[str, int]:
    """Name/node counts of the standard bench fixture (the zone the
    headline axes serve)."""
    paths = set()
    for p in FIXTURE:
        parts = [x for x in p.split("/") if x]
        for i in range(1, len(parts) + 1):
            paths.add("/".join(parts[:i]))
    return {"names": len(FIXTURE), "nodes": len(paths)}
N_QUERIES = int(os.environ.get("BENCH_QUERIES", "50000"))
# hot-axis passes: p99 on a single shared-core box varies ±40% run to
# run (see docs/bench.md), so the headline is the median-by-qps of
# BENCH_PASSES passes and the JSON carries the spread
N_PASSES = int(os.environ.get("BENCH_PASSES", "3"))
# miss axis: distinct names, each queried exactly once (cache-cold)
N_MISS = int(os.environ.get("BENCH_MISS_QUERIES", "20000"))
CONCURRENCY = int(os.environ.get("BENCH_CONCURRENCY", "64"))
# overridable so `make bench-smoke` (reduced iteration CI gate) can't
# pollute the persisted baseline with small-sample figures
BASELINE_FILE = os.environ.get(
    "BENCH_BASELINE_FILE", os.path.join(ROOT, "BENCH_BASELINE.json"))

# query mix mirroring BASELINE.json's proxy configs; shared by the native
# and Python load drivers so both measure the same workload
BENCH_MIX = [
    ("web.bench.com", Type.A),
    ("svc.bench.com", Type.A),
    ("_http._tcp.svc.bench.com", Type.SRV),
    ("1.0.1.10.in-addr.arpa", Type.PTR),
]

FIXTURE = {
    "/com/bench/web": {"type": "host", "host": {"address": "10.1.0.1"}},
    "/com/bench/svc": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 8080},
    },
    **{f"/com/bench/svc/lb{i}":
       {"type": "load_balancer",
        "load_balancer": {"address": f"10.1.1.{i + 1}"}}
       for i in range(8)},
}


class BenchClient(asyncio.DatagramProtocol):
    """Windowed UDP load generator with timeout-retransmit (loopback UDP
    still drops under bursts; a stalled window would hang the run)."""

    RETRY_AFTER = 1.0

    def __init__(self, queries: List[bytes], done: asyncio.Future) -> None:
        self.queries = queries
        self.done = done
        self.next_idx = 0
        self.received = 0
        self.latencies: List[float] = []
        self.outstanding: Dict[int, float] = {}   # qid -> last-sent-at
        self.retried: set = set()   # qids whose latency is tainted
        self.errors = 0
        self.retries = 0

    def connection_made(self, transport) -> None:
        self.transport = transport
        for _ in range(min(CONCURRENCY, len(self.queries))):
            self._send_next()

    def _send_next(self) -> None:
        i = self.next_idx
        if i >= len(self.queries):
            return
        self.next_idx += 1
        self.outstanding[i] = time.perf_counter()
        self.transport.sendto(self.queries[i])

    def retransmit_stale(self) -> None:
        now = time.perf_counter()
        for qid, t0 in list(self.outstanding.items()):
            if now - t0 > self.RETRY_AFTER:
                self.retries += 1
                self.retried.add(qid)   # latency not counted
                self.outstanding[qid] = now   # keep retrying until answered
                self.transport.sendto(self.queries[qid])

    def datagram_received(self, data, addr) -> None:
        now = time.perf_counter()
        qid = (data[0] << 8) | data[1]
        t0 = self.outstanding.pop(qid, None)
        if t0 is None:
            return   # duplicate response to a retransmit
        if qid not in self.retried:
            self.latencies.append(now - t0)
        if data[3] & 0x0F:   # rcode nibble
            self.errors += 1
        self.received += 1
        if self.received >= len(self.queries):
            if not self.done.done():
                self.done.set_result(None)
        else:
            self._send_next()


def _bench_env() -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _launch_server(config: str) -> subprocess.Popen:
    """The one place a bench server process is spawned — every axis
    must run the identical launch incantation."""
    return subprocess.Popen(
        _pin("server")
        + [sys.executable, "-u", "-m", "binder_tpu.main", "-f", config,
           "-p", "0"],
        cwd=ROOT, env=_bench_env(), stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)


def _reap(proc: subprocess.Popen) -> None:
    """terminate -> bounded wait -> kill; a wedged child must never
    survive to compete with later axes for the shared core."""
    try:
        proc.terminate()
        proc.wait(timeout=10)
    except Exception:
        try:
            proc.kill()
            proc.wait(timeout=10)
        except Exception:
            pass


def start_server(tmpdir: str) -> subprocess.Popen:
    fixture = os.path.join(tmpdir, "fixture.json")
    config = os.path.join(tmpdir, "config.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    with open(config, "w") as f:
        json.dump({
            "dnsDomain": "bench.com", "datacenterName": "dc0",
            "host": "127.0.0.1",
            "store": {"backend": "fake", "fixture": fixture},
            "queryLog": False,
        }, f)
    return _launch_server(config)


def _wait_for_line_buf(proc: subprocess.Popen, pattern: bytes,
                       what: str, timeout: float = 30.0
                       ) -> Tuple[int, bytes]:
    """Deadline-bounded read of proc stdout until `pattern` matches;
    returns (captured int, everything read so far).  A child that
    wedges mid-startup (or writes a partial line) must not hang the
    bench."""
    deadline = time.time() + timeout
    buf = b""
    while time.time() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(0.0, deadline - time.time()))
        if not ready:
            break
        chunk = os.read(proc.stdout.fileno(), 4096)
        if not chunk:
            raise RuntimeError("%s exited during startup" % what)
        buf += chunk
        m = re.search(pattern, buf)
        if m:
            return int(m.group(1)), buf
    raise RuntimeError("%s did not report its port within %.0fs"
                       % (what, timeout))


def _wait_for_line(proc: subprocess.Popen, pattern: bytes,
                   what: str, timeout: float = 30.0) -> int:
    return _wait_for_line_buf(proc, pattern, what, timeout)[0]


def wait_for_port(proc: subprocess.Popen, timeout: float = 30.0) -> int:
    # patterns must anchor past the number, or a mid-number pipe-buffer
    # split ("...:444" / "28\"...") yields a truncated port; the bunyan
    # msg is JSON, so the port is terminated by the closing quote
    return _wait_for_line(
        proc, rb"UDP DNS service started on [\d.]+:(\d+)\"",
        "bench server", timeout)


def wait_for_ports(proc: subprocess.Popen) -> Tuple[int, int]:
    """(UDP port, metrics scrape port).  The metrics line is logged
    before the UDP line (main.py startup order) and the pipe preserves
    order, so by the time the UDP pattern matches, the metrics line is
    already in the buffer."""
    port, buf = _wait_for_line_buf(
        proc, rb"UDP DNS service started on [\d.]+:(\d+)\"",
        "bench server")
    m = re.search(rb"metrics server started on port (\d+)\"", buf)
    if m is None:
        raise RuntimeError("bench server did not report a metrics port")
    return port, int(m.group(1))


async def _drive(port: int) -> Dict[str, float]:
    # qids must be unique across the in-flight window; id space is 64k
    assert N_QUERIES <= 65536
    queries = [make_query(*BENCH_MIX[i % len(BENCH_MIX)],
                          qid=i % 65536).encode()
               for i in range(N_QUERIES)]

    loop = asyncio.get_running_loop()
    done = loop.create_future()
    t0 = time.perf_counter()
    transport, proto = await loop.create_datagram_endpoint(
        lambda: BenchClient(queries, done),
        remote_addr=("127.0.0.1", port))

    async def watchdog():
        while not done.done():
            await asyncio.sleep(0.25)
            proto.retransmit_stale()

    wd = asyncio.ensure_future(watchdog())
    await asyncio.wait_for(done, timeout=300)
    elapsed = time.perf_counter() - t0
    wd.cancel()
    transport.close()

    lats = sorted(proto.latencies)
    return {
        "qps": N_QUERIES / elapsed,
        "elapsed_s": elapsed,
        "errors": proto.errors,
        "retries": proto.retries,
        "p50_us": lats[len(lats) // 2] * 1e6,
        "p99_us": lats[int(len(lats) * 0.99)] * 1e6,
    }


DNSBLAST = os.path.join(ROOT, "native", "build", "dnsblast")


def _wait_for_file_line(path: str, pattern: bytes, what: str,
                        proc: subprocess.Popen) -> int:
    """Poll a log FILE for `pattern` (used when the server's stdout is a
    real file, not a pipe — the logged axis must not let an undrained
    pipe block the server's log writes)."""
    deadline = time.time() + 30
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError("%s exited during startup" % what)
        try:
            with open(path, "rb") as f:
                m = re.search(pattern, f.read())
            if m:
                return int(m.group(1))
        except OSError:
            pass
        time.sleep(0.05)
    raise RuntimeError("%s did not report its port within 30s" % what)


N_TCP1 = int(os.environ.get("BENCH_TCP1_QUERIES", "5000"))
N_TC_FLOWS = int(os.environ.get("BENCH_TC_FLOWS", "1500"))


async def _tc_retry_flows(port: int, n_flows: int,
                          conc: int = 6) -> Dict[str, float]:
    """The tc=1 flow a no-EDNS UDP client actually runs: UDP query ->
    truncated response -> RFC 1035 TCP retry -> full answer.  Driven
    from Python (the flow is latency-bound, not packet-rate-bound);
    each flow's latency covers both legs including the TCP connect.

    The client is deliberately LEAN — raw sockets via loop.sock_*, and
    header-level validation (TC bit, id, ancount) instead of a full
    per-flow Message.decode — because the measured p50 is
    conc x (client + server CPU) on the shared core: a heavy client
    measures itself, not the serve path (the r05 figure's 10.8ms was
    mostly asyncio-streams + decode cost queued 16 deep).  One sampled
    flow per run still gets the full decode/compare, so wire
    correctness stays asserted."""
    from binder_tpu.dns import Message as _M

    loop = asyncio.get_running_loop()
    pending: dict = {}

    class _Udp(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            self.transport = transport

        def datagram_received(self, data, addr):
            fut = pending.pop((data[0] << 8) | data[1], None)
            if fut is not None and not fut.done():
                fut.set_result(data)

    transport, proto = await loop.create_datagram_endpoint(
        _Udp, remote_addr=("127.0.0.1", port))
    wire = bytearray(make_query("big.bench.com", Type.A,
                                edns_payload=None).encode())
    sem = asyncio.Semaphore(conc)
    lats: List[float] = []
    errors = 0
    sampled: List[bytes] = []

    async def one(i: int) -> None:
        nonlocal errors
        async with sem:
            t0 = time.perf_counter()
            q = bytes((i >> 8, i & 0xFF)) + bytes(wire[2:])
            fut = loop.create_future()
            pending[i] = fut
            proto.transport.sendto(q)
            try:
                resp = await asyncio.wait_for(fut, 5.0)
            except asyncio.TimeoutError:
                errors += 1
                return
            if not (resp[2] & 0x02):     # expected TC on the UDP leg
                errors += 1
                return
            s = _socket_mod.socket(_socket_mod.AF_INET,
                                   _socket_mod.SOCK_STREAM)
            s.setblocking(False)

            async def tcp_leg() -> Optional[bytes]:
                await loop.sock_connect(s, ("127.0.0.1", port))
                await loop.sock_sendall(
                    s, len(q).to_bytes(2, "big") + q)
                body = b""
                need = None
                while need is None or len(body) < need:
                    chunk = await loop.sock_recv(s, 65536)
                    if not chunk:
                        return None
                    body += chunk
                    if need is None and len(body) >= 2:
                        need = 2 + ((body[0] << 8) | body[1])
                return body

            try:
                # ONE watchdog around the whole leg: per-op wait_for
                # wrappers cost ~15µs each in task/timer machinery,
                # which the conc-deep queue multiplies into the p50
                body = await asyncio.wait_for(tcp_leg(), 5.0)
            except (OSError, asyncio.TimeoutError):
                errors += 1
                return
            finally:
                s.close()
            if body is None:
                errors += 1
                return
            # header-level checks: id echo, QR, TC clear, answers
            if (body[2:4] != q[:2] or not (body[4] & 0x80)
                    or (body[4] & 0x02)
                    or (body[8] << 8 | body[9]) == 0):
                errors += 1
                return
            lats.append(time.perf_counter() - t0)
            if not sampled:
                sampled.append(body[2:])

    t0 = time.perf_counter()
    await asyncio.gather(*[one(i) for i in range(n_flows)])
    elapsed = time.perf_counter() - t0
    transport.close()
    if sampled:
        # full decode on the sampled flow: the lean header checks must
        # never hide a malformed wire
        m = _M.decode(sampled[0])
        if m.tc or not m.answers:
            errors += 1
    lats.sort()
    return {
        "flows_per_s": n_flows / elapsed,
        "p50_us": (lats[len(lats) // 2] * 1e6) if lats else None,
        "p99_us": (lats[int(len(lats) * 0.99)] * 1e6) if lats else None,
        "errors": errors,
    }


def _bench_tcp(tmpdir: str) -> Dict[str, float]:
    """TCP serving axis (the reference serves TCP on the same port,
    lib/server.js:643-653): persistent pipelined connections (tcp_qps),
    one-connection-per-query (tcp1_qps, the non-keep-alive client
    cost), and the tc=1 UDP->TCP retry flow for answers that truncate
    at the classic 512-byte ceiling.

    Interleaved A/B (the fix that tamed the balancer-overhead axis in
    round 5): UDP passes (A, the in-window control) alternate with TCP
    passes (B, the measured lane) against ONE server inside one time
    window, so box drift lands in both sides and cancels out of the
    `vs_udp` ratio.  The r05 scheme measured TCP passes back to back
    and its 29k spread on a 199k mean was mostly the box, not the lane;
    the spread is still reported honestly, but the ratio is the
    stable figure."""
    fixture = os.path.join(tmpdir, "tcp_fixture.json")
    fix = dict(FIXTURE)
    # an answer set that must truncate for no-EDNS UDP clients
    fix["/com/bench/big"] = {
        "type": "service",
        "service": {"srvce": "_big", "proto": "_tcp", "port": 80}}
    for i in range(40):
        fix[f"/com/bench/big/b{i:02d}"] = {
            "type": "load_balancer",
            "load_balancer": {"address": f"10.30.0.{i + 1}"}}
    with open(fixture, "w") as f:
        json.dump(fix, f)
    config = os.path.join(tmpdir, "tcp_config.json")
    with open(config, "w") as f:
        json.dump({"dnsDomain": "bench.com", "datacenterName": "dc0",
                   "host": "127.0.0.1",
                   "store": {"backend": "fake", "fixture": fixture},
                   "queryLog": False}, f)
    proc = _launch_server(config)
    try:
        # wait for the TCP listener line directly (same port as UDP —
        # the pair bind guarantees it); two sequential waits would race
        # the pipe buffer (the first read may consume both lines)
        port = _wait_for_line(
            proc, rb"TCP DNS service started on [\d.]+:(\d+)\"",
            "bench server tcp listener")
        tmpl = os.path.join(tmpdir, "tcp_queries.bin")
        _write_templates(tmpl, BENCH_MIX)
        _drive_native(port, tmpdir, tmpl_path=tmpl)              # warm A
        _drive_native(port, tmpdir, tmpl_path=tmpl, mode="tcp")  # warm B
        rounds = max(3, N_PASSES)
        upasses: List[Dict[str, float]] = []
        tpasses: List[Dict[str, float]] = []
        for _ in range(rounds):
            upasses.append(_drive_native(port, tmpdir, tmpl_path=tmpl))
            tpasses.append(_drive_native(port, tmpdir, tmpl_path=tmpl,
                                         mode="tcp"))

        def med(passes):
            passes = sorted(passes, key=lambda r: r["qps"])
            r = dict(passes[len(passes) // 2])
            r["qps_spread"] = round(
                passes[-1]["qps"] - passes[0]["qps"], 1)
            p99s = [p["p99_us"] for p in passes]
            r["p99_spread_us"] = round(max(p99s) - min(p99s), 1)
            r["passes"] = len(passes)
            return r

        res = med(tpasses)
        umed = med(upasses)
        # drift-cancelling figure: per-adjacent-pair ratio, median —
        # both sides of each pair saw the same thermal/scheduler
        # environment
        ratios = sorted(t["qps"] / u["qps"]
                        for t, u in zip(tpasses, upasses))
        res["vs_udp"] = round(ratios[len(ratios) // 2], 3)
        res["udp_ref_qps"] = round(umed["qps"], 1)
        t1passes = [_drive_native(port, tmpdir, tmpl_path=tmpl,
                                  n=N_TCP1, mode="tcp1")
                    for _ in range(3)]
        t1 = sorted(t1passes, key=lambda r: r["qps"])[1]
        res["tcp1_qps"] = round(t1["qps"], 1)
        res["tcp1_qps_spread"] = round(
            max(p["qps"] for p in t1passes)
            - min(p["qps"] for p in t1passes), 1)
        res["tcp1_p99_us"] = round(t1["p99_us"], 1)
        tc = asyncio.run(_tc_retry_flows(port, N_TC_FLOWS))
        if tc["errors"] == 0:
            res["tc_retry_flows_per_s"] = round(tc["flows_per_s"], 1)
            res["tc_retry_p50_us"] = round(tc["p50_us"], 1)
        else:
            print(f"bench: tc-retry flow errors: {tc['errors']}",
                  file=sys.stderr)
        return res
    finally:
        _reap(proc)


def _bench_logged(tmpdir: str) -> Dict[str, float]:
    """Hit-path throughput in the REFERENCE-PARITY posture: per-query
    logging ON (the reference logs every query unconditionally,
    lib/server.js:537-591).  Round 5's native log ring keeps the C serve
    path active here — entries carry pre-rendered JSON fragments and the
    C side appends complete lines to a ring Python drains in batches —
    so this axis measures what operators actually get, not a log-off
    special case.  stdout goes to a real file (the posture's log volume
    would deadlock an undrained pipe) and the line count is reported so
    the 'every query leaves a record' property is load-verified, not
    assumed."""
    fixture = os.path.join(tmpdir, "fixture_logged.json")
    config = os.path.join(tmpdir, "config_logged.json")
    logpath = os.path.join(tmpdir, "logged.out")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    with open(config, "w") as f:
        json.dump({
            "dnsDomain": "bench.com", "datacenterName": "dc0",
            "host": "127.0.0.1",
            "store": {"backend": "fake", "fixture": fixture},
            "queryLog": True,
        }, f)
    logf = open(logpath, "wb")
    try:
        proc = subprocess.Popen(
            _pin("server")
            + [sys.executable, "-u", "-m", "binder_tpu.main", "-f",
               config, "-p", "0"],
            cwd=ROOT, env=_bench_env(), stdout=logf,
            stderr=subprocess.DEVNULL)
        try:
            port = _wait_for_file_line(
                logpath,
                rb"UDP DNS service started on [\d.]+:(\d+)\"",
                "logged bench server", proc)
            res = _median_passes(
                lambda: _drive_native(port, tmpdir), N_PASSES)
        finally:
            _reap(proc)
    finally:
        logf.close()
    n_lines = 0
    with open(logpath, "rb") as f:
        for ln in f:
            if b'"DNS query"' in ln:
                n_lines += 1
    res["log_lines"] = n_lines
    return res


def _write_templates(path: str, mix, rd: bool = False) -> None:
    with open(path, "wb") as f:
        for name, qtype in mix:
            wire = make_query(name, qtype, qid=0, rd=rd).encode()
            f.write(len(wire).to_bytes(2, "big") + wire)


def _drive_native(port: int, tmpdir: str, tmpl_path: str = None,
                  n: int = None, mode: str = "udp",
                  conns: int = 8, sources: int = 1) -> Dict[str, float]:
    """Drive load with the C++ generator (native/loadgen/dnsblast.cpp).

    On a single-core box the Python client's interpreter cost competes
    with the server for the same CPU; the native client keeps measurement
    overhead negligible so the number reported is server capacity.
    Modes: udp (default), tcp (persistent pipelined connections), tcp1
    (one connection per query).  ``sources`` spreads UDP load over that
    many distinct loopback source addresses (dnsblast -S) so per-client
    admission limits see a client population, not one mega-client."""
    if tmpl_path is None:
        tmpl_path = os.path.join(tmpdir, "queries.bin")
        _write_templates(tmpl_path, BENCH_MIX)
    n = N_QUERIES if n is None else n
    assert n <= 65536, "dnsblast qid/state space"
    extra = [] if mode == "udp" else ["-m", mode, "-T", str(conns)]
    if sources > 1 and mode == "udp":
        extra += ["-S", str(sources)]
    out = subprocess.run(
        _pin("client")
        + [DNSBLAST, "-p", str(port), "-n", str(n),
           "-w", str(CONCURRENCY), "-t", tmpl_path] + extra,
        capture_output=True, text=True, timeout=330, check=True)
    return json.loads(out.stdout)


def _median_passes(drive, passes: int) -> Dict[str, float]:
    """Run `drive` N times; return the median-by-qps pass annotated with
    the qps and p99 spreads across passes — EVERY multi-pass axis
    carries its own noise band (VERDICT r3 item 1), so a cross-round
    delta inside the band is never mistaken for a regression."""
    results = [drive() for _ in range(passes)]
    results.sort(key=lambda r: r["qps"])
    res = dict(results[len(results) // 2])
    res["qps_spread"] = round(results[-1]["qps"] - results[0]["qps"], 1)
    p99s = [r["p99_us"] for r in results]
    res["p99_spread_us"] = round(max(p99s) - min(p99s), 1)
    res["passes"] = passes
    return res


def _read_balancer_stats(sockdir: str) -> Dict[str, object]:
    """One shot of the balancer's stats socket (docs/balancer-protocol.md)."""
    s = _socket_mod.socket(_socket_mod.AF_UNIX)
    s.settimeout(5)
    try:
        s.connect(os.path.join(sockdir, ".balancer.stats"))
        buf = b""
        while True:
            chunk = s.recv(4096)
            if not chunk:
                break
            buf += chunk
    finally:
        s.close()
    return json.loads(buf)


_PRECOMPILE_LINE = re.compile(
    r'^binder_precompile_([a-z_]+)(?:\{[^}]*\})? ([0-9.eE+-]+)$', re.M)


def _scrape_precompile(metrics_port: int) -> Dict[str, float]:
    """The `binder_precompile_*` family off a bench server's scrape
    endpoint — the mutation-time pipeline's economics (compiled / shed /
    serves / queue depth), so a churn or miss figure's movement is
    attributable to the precompiler doing (or shedding) its work."""
    import urllib.request
    with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/metrics", timeout=5) as r:
        text = r.read().decode()
    out: Dict[str, float] = {}
    for name, value in _PRECOMPILE_LINE.findall(text):
        out[name] = out.get(name, 0.0) + float(value)
    return out


_SHED_LINE = re.compile(
    r'^binder_shed_total\{[^}]*reason="([^"]+)"[^}]*\} ([0-9.eE+-]+)$',
    re.M)
_RRL_LINE = re.compile(
    r'^binder_rrl_([a-z_]+)(?:\{[^}]*\})? ([0-9.eE+-]+)$', re.M)


def _scrape_shed(metrics_port: int) -> Dict[str, float]:
    """`binder_shed_total` by reason off a bench server's scrape —
    under production admission limits, sheds are posture, and an axis
    that can shed must attribute its errors."""
    import urllib.request
    with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/metrics", timeout=5) as r:
        text = r.read().decode()
    out: Dict[str, float] = {}
    for reason, value in _SHED_LINE.findall(text):
        v = float(value)
        if v:
            out[reason] = out.get(reason, 0.0) + v
    return out


def _scrape_rrl(metrics_port: int) -> Dict[str, float]:
    """The `binder_rrl_*` family off a bench server's scrape — the
    hostile axis' server-side shed/slip attribution."""
    import urllib.request
    with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/metrics", timeout=5) as r:
        text = r.read().decode()
    out: Dict[str, float] = {}
    for name, value in _RRL_LINE.findall(text):
        out[name] = out.get(name, 0.0) + float(value)
    return out


_STAGE_LINE = re.compile(
    r'^binder_query_stage_seconds_(sum|count)'
    r'\{[^}]*stage="([^"]+)"[^}]*\} ([0-9.eE+-]+)$', re.M)


def _scrape_stage_seconds(metrics_port: int) -> Dict[str, Dict[str, float]]:
    """Read the per-stage attribution histogram off a bench server's
    scrape endpoint: {stage: {"sum_s": total seconds, "count": N}}.
    This is the same `binder_query_stage_seconds` any production
    Prometheus sees — the bench consumes the real exposition text, not
    a side channel."""
    import urllib.request
    with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/metrics", timeout=5) as r:
        text = r.read().decode()
    stages: Dict[str, Dict[str, float]] = {}
    for kind, stage, value in _STAGE_LINE.findall(text):
        cell = stages.setdefault(stage, {"sum_s": 0.0, "count": 0.0})
        cell["sum_s" if kind == "sum" else "count"] += float(value)
    return stages


def _attribution_from_stages(
        stages: Dict[str, Dict[str, float]]) -> Optional[Dict[str, object]]:
    """Per-stage attribution block from scraped stage seconds: mean ms
    per observed query, share of total attributed time, and the owning
    stage.  The cursor stamp "await" spans the whole dispatch→callback
    wait and is already decomposed by the overlay phases "upstream-rtt"
    + "loop-wait" (recursion fast path), so it is excluded from the
    share denominator whenever the split exists — otherwise the wait
    would be counted twice and the shares would be meaningless."""
    exclusive = {k: v for k, v in stages.items() if v["sum_s"] > 0}
    if "upstream-rtt" in exclusive:
        exclusive.pop("await", None)
    total = sum(v["sum_s"] for v in exclusive.values())
    if not total:
        return None
    mean_ms = {k: round(v["sum_s"] / v["count"] * 1000.0, 4)
               for k, v in stages.items() if v["count"]}
    share = {k: round(100.0 * v["sum_s"] / total, 1)
             for k, v in exclusive.items()}
    owner = max(exclusive, key=lambda k: exclusive[k]["sum_s"])
    return {"mean_ms": mean_ms, "share_pct": share, "owner": owner}


def _balancer_attribution(
        stats: Dict[str, object]) -> Optional[Dict[str, object]]:
    """Attribution block from the balancer's stage_cycles counters
    (docs/balancer-protocol.md): share of the balancer's own packet
    path per stage, per-op µs via the calibrated TSC rate, and the
    owning stage."""
    cells = stats.get("stage_cycles") or {}
    cycles_per_us = stats.get("cycles_per_us") or 0
    total = sum(c.get("cycles", 0) for c in cells.values())
    if not total:
        return None
    share = {k: round(100.0 * c.get("cycles", 0) / total, 1)
             for k, c in cells.items()}
    us_per_op = {k: round(c["cycles"] / c["ops"] / cycles_per_us, 3)
                 for k, c in cells.items()
                 if c.get("ops") and cycles_per_us}
    owner = max(cells, key=lambda k: cells[k].get("cycles", 0))
    return {"share_pct": share, "us_per_op": us_per_op, "owner": owner}


def _rtt_p99_us(stats: Dict[str, object]) -> object:
    """p99 upper bound from the balancer's log2-µs RTT cells; None when
    the p99 observation lands in the open-ended last cell (no honest
    upper bound exists — `balstat` prints it as >=16384us)."""
    n = stats.get("fwd_rtt_count", 0)
    cells = stats.get("fwd_rtt_us_cells") or []
    if not n or not cells:
        return None
    run = 0
    for i, c in enumerate(cells):
        run += c
        if run >= 0.99 * n:
            return float(1 << i) if i < len(cells) - 1 else None
    return None


def _bench_miss(tmpdir: str) -> Dict[str, float]:
    """Cache-cold axis: N_MISS distinct names, each queried exactly once
    against a fresh server — answer-cache/fast-path reuse is
    structurally impossible.  Since round 4 the production cold path for
    host records is the precompiled zone table (fpcore.h): the mirror
    pushes finished answers at build time, so first queries serve from
    the C drain.  The axis therefore measures what a user actually gets
    on a cold name.  The sub-figures make the precompile layers
    attributable (this round's mutation-time answer precompilation):

    - `engine_qps` re-runs with `zonePrecompile: false` — the Python
      serve path, which now answers cold names from the mutation-time
      precompiled answer table (`resolver/precompile.py`, seeded from
      the mirror at start): a dict probe + ID/flags patch per query;
    - `lazy_qps` additionally sets `answerPrecompile: false` — the bare
      resolve-per-query path every shape took before this round, kept
      as the engine's own regression gate.

    The precompiled-path configs size the compiled table to the fixture
    (`precompileSize`), as an operator sizing for a zone would; the
    per-key cache and the native arena stay at their defaults so the
    production-path figures remain comparable across rounds.  Fresh
    server per pass; median of N_PASSES."""
    fixture = os.path.join(tmpdir, "miss_fixture.json")
    with open(fixture, "w") as f:
        json.dump({f"/com/bench/m{i}": {
            "type": "host",
            "host": {"address":
                     f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"}}
            for i in range(N_MISS)}, f)
    tmpl = os.path.join(tmpdir, "miss_queries.bin")
    _write_templates(tmpl, [(f"m{i}.bench.com", Type.A)
                            for i in range(N_MISS)])

    def axis(zone: bool, precompile: bool = True) -> Dict[str, float]:
        config = os.path.join(
            tmpdir, f"miss_config_{int(zone)}{int(precompile)}.json")
        cfg = {"dnsDomain": "bench.com", "datacenterName": "dc0",
               "host": "127.0.0.1",
               "store": {"backend": "fake", "fixture": fixture},
               "queryLog": False, "zonePrecompile": zone,
               "answerPrecompile": precompile,
               # room for every seeded name (A + PTR shapes)
               "precompileSize": 3 * N_MISS}
        if not zone:
            # the engine/lazy pair sizes the answer cache (Python
            # per-key AND the C arena the mutation-time installs land
            # in) to the fixture — the attribution comparison needs
            # both sides identically configured; the production (zone)
            # figure keeps the default size for cross-round
            # comparability
            cfg["size"] = 8 * N_MISS
        with open(config, "w") as f:
            json.dump(cfg, f)

        def one_pass() -> Dict[str, float]:
            proc = _launch_server(config)
            try:
                port = wait_for_port(proc)
                return _drive_native(port, tmpdir, tmpl_path=tmpl,
                                     n=N_MISS)
            finally:
                _reap(proc)

        return _median_passes(one_pass, N_PASSES)

    res = axis(zone=True)
    try:
        eng = axis(zone=False)
        res["engine_qps"] = round(eng["qps"], 1)
        res["engine_qps_spread"] = eng.get("qps_spread")
        res["engine_p99_us"] = round(eng["p99_us"], 1)
    except Exception as e:  # noqa: BLE001 — sub-figure is supplementary
        print(f"bench: miss engine sub-axis failed: {e!r}",
              file=sys.stderr)
    try:
        lazy = axis(zone=False, precompile=False)
        res["lazy_qps"] = round(lazy["qps"], 1)
        res["lazy_qps_spread"] = lazy.get("qps_spread")
        res["lazy_p99_us"] = round(lazy["p99_us"], 1)
    except Exception as e:  # noqa: BLE001 — sub-figure is supplementary
        print(f"bench: miss lazy sub-axis failed: {e!r}",
              file=sys.stderr)
    return res


# ---------------------------------------------------------------------------
# Churn axis: hot mix under continuous store mutation, through the REAL
# ZooKeeper wire protocol (in-process ZKTestServer), so the measurement
# covers watch delivery, mirror updates, generation bumps, and answer/fast
# path invalidation — the production cache-coherence path.

N_CHURN_HOSTS = 64            # hosts the churner rewrites round-robin
CHURN_INTERVAL_S = 0.002      # ~500 mutations/s offered


def _wait_ready(port: int, probe: bytes, what: str,
                deadline_s: float = 15.0) -> None:
    """Poll the server with one probe query until it answers NOERROR —
    the first queries SERVFAIL (or time out) until the mirror / the
    recursion path is actually serving."""
    import socket as _s
    s = _s.socket(_s.AF_INET, _s.SOCK_DGRAM)
    s.settimeout(0.5)
    s.connect(("127.0.0.1", port))
    deadline = time.time() + deadline_s
    try:
        while True:
            try:
                s.send(probe)
                resp = s.recv(512)
                if not (resp[3] & 0x0F):
                    return
            except _s.timeout:
                pass
            if time.time() > deadline:
                raise RuntimeError(f"{what} never became ready")
            time.sleep(0.1)
    finally:
        s.close()


async def _bench_churn_async(tmpdir: str) -> Dict[str, float]:
    from binder_tpu.store.zk_client import ZKClient

    zk_proc = subprocess.Popen(
        _pin("server")
        + [sys.executable, "-u", "-m", "binder_tpu.store.zk_testserver",
           "0"],
        cwd=ROOT, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=_bench_env())
    srv_proc = None
    writer = None
    try:
        # anchor past the number: a pipe-buffer split mid-digits must
        # not yield a truncated port (see wait_for_port)
        zk_port = _wait_for_line(
            zk_proc, rb"listening on 127\.0\.0\.1:(\d+)\n",
            "zk-testserver")

        # seed the tree through the real client (registrar analog)
        writer = ZKClient(address="127.0.0.1", port=zk_port)
        writer.start()
        deadline = time.time() + 10
        while not writer.is_connected():
            if time.time() > deadline:
                raise RuntimeError("zk seed client did not connect")
            await asyncio.sleep(0.02)
        for path, obj in FIXTURE.items():
            await writer.mkdirp(path, json.dumps(obj).encode())
        for i in range(N_CHURN_HOSTS):
            await writer.mkdirp(
                f"/com/bench/churn{i}",
                json.dumps({"type": "host",
                            "host": {"address": f"10.9.0.{i + 1}"}}
                           ).encode())

        # unique per attempt: the axis retry (_try_axis) must not die
        # on a directory a failed first attempt left behind
        churn_sockdir = tempfile.mkdtemp(dir=tmpdir,
                                         prefix="churn_sock")
        config = os.path.join(tmpdir, "churn_config.json")
        with open(config, "w") as f:
            json.dump({"dnsDomain": "bench.com", "datacenterName": "dc0",
                       "host": "127.0.0.1",
                       "store": {"backend": "zookeeper",
                                 "host": "127.0.0.1", "port": zk_port},
                       "queryLog": False,
                       # also serve the balancer socket so the same
                       # churn run can measure the balancer-fronted path
                       # (per-name opcode-1 invalidation)
                       "balancerSocket": os.path.join(churn_sockdir,
                                                      "0")}, f)
        srv_proc = _launch_server(config)
        port, mport = wait_for_ports(srv_proc)

        # wait until the mirror actually serves (first queries SERVFAIL
        # until the watch tree is built); blocking is fine — the churner
        # does not exist yet
        await asyncio.to_thread(
            _wait_ready, port, make_query(*BENCH_MIX[0], qid=1).encode(),
            "server over zk")

        tmpl = os.path.join(tmpdir, "churn_queries.bin")
        _write_templates(tmpl, BENCH_MIX)

        mutations = 0
        stop = asyncio.Event()

        async def churner():
            nonlocal mutations
            i = 0
            while not stop.is_set():
                i += 1
                await writer.set_data(
                    f"/com/bench/churn{i % N_CHURN_HOSTS}",
                    json.dumps({"type": "host",
                                "host": {"address":
                                         f"10.9.{i % 250}.{i % 250 + 1}"}}
                               ).encode())
                mutations += 1
                await asyncio.sleep(CHURN_INTERVAL_S)

        churn_task = asyncio.ensure_future(churner())
        t0 = time.perf_counter()
        total = 0
        p99s = []
        p50s = []
        wqps = []
        for _ in range(3):   # ~3 windows of 50k under sustained churn
            blast = await asyncio.create_subprocess_exec(
                *_pin("client"), DNSBLAST,
                "-p", str(port), "-n", str(N_QUERIES),
                "-w", str(CONCURRENCY), "-t", tmpl,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL)
            out, _ = await blast.communicate()
            if blast.returncode != 0:
                raise RuntimeError("dnsblast failed under churn")
            r = json.loads(out)
            total += N_QUERIES
            p99s.append(r["p99_us"])
            p50s.append(r["p50_us"])
            wqps.append(r["qps"])
        elapsed = time.perf_counter() - t0
        # snapshot with elapsed: the churner keeps running through the
        # windows below, and a later read would inflate the
        # mutations/s figure
        direct_mutations = mutations

        # Mixed sub-figure (the precompile-aware churn measurement):
        # the SAME sustained churn, but the query mix now includes the
        # churning names themselves — every one of their cached answers
        # is invalidated several times per second, so this window
        # measures invalidate-then-requery, the path mutation-time
        # precompilation exists for.  Warm window, then the measured
        # one.  Supplementary: a failure drops only these figures.
        mixed_qps = mixed_p50 = mixed_p99 = None
        try:
            mixed_tmpl = os.path.join(tmpdir, "churn_mixed_queries.bin")
            _write_templates(
                mixed_tmpl,
                BENCH_MIX + [(f"churn{i}.bench.com", Type.A)
                             for i in range(N_CHURN_HOSTS)])
            for _ in range(2):
                blast = await asyncio.create_subprocess_exec(
                    *_pin("client"), DNSBLAST,
                    "-p", str(port), "-n", str(N_QUERIES),
                    "-w", str(CONCURRENCY), "-t", mixed_tmpl,
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.DEVNULL)
                out, _ = await blast.communicate()
                if blast.returncode != 0:
                    raise RuntimeError(
                        "dnsblast failed under mixed churn")
                r = json.loads(out)
            mixed_qps, mixed_p50 = r["qps"], r["p50_us"]
            mixed_p99 = r["p99_us"]
        except Exception as e:  # noqa: BLE001 — supplementary figures
            print(f"bench: mixed churn sub-axis failed: {e!r}",
                  file=sys.stderr)

        # precompile attribution for the windows just measured: did the
        # mutation-time pipeline keep up (compiled tracking the mutated
        # hot shapes, shed 0) or degrade to lazy (shed > 0)?
        precompile = None
        try:
            precompile = _scrape_precompile(mport)
        except Exception as e:  # noqa: BLE001 — supplementary figure
            print(f"bench: precompile scrape failed: {e!r}",
                  file=sys.stderr)

        # balancer-fronted path under the same sustained churn: the
        # opcode-1 per-name invalidation keeps the balancer cache hot
        # for the unmutated names (docs/balancer-protocol.md).  First
        # window warms the balancer cache, the second is reported.
        # Supplementary like the topology axis: a failure here logs and
        # drops only these figures, never the already-measured direct
        # churn numbers.
        topo_qps = topo_p99 = None
        bal = None
        if os.access(MBALANCER, os.X_OK):
            try:
                # launch + PORT wait off-loop: a wedged balancer must not
                # stall the churner/ZK pings for the 30s line deadline
                bal, bal_port = await asyncio.to_thread(
                    _launch_balancer, churn_sockdir)
                await asyncio.sleep(0.5)   # backend scan + connect
                for i in range(2):
                    blast = await asyncio.create_subprocess_exec(
                        *_pin("client"), DNSBLAST,
                        "-p", str(bal_port), "-n",
                        str(N_QUERIES), "-w", str(CONCURRENCY),
                        "-t", tmpl,
                        stdout=asyncio.subprocess.PIPE,
                        stderr=asyncio.subprocess.DEVNULL)
                    out, _ = await blast.communicate()
                    if blast.returncode != 0:
                        raise RuntimeError(
                            "dnsblast failed under balancer churn")
                    r = json.loads(out)
                topo_qps = r["qps"]
                topo_p99 = r["p99_us"]
            except Exception as e:  # noqa: BLE001 — supplementary axis
                print(f"bench: balancer-churn axis failed: {e!r}",
                      file=sys.stderr)
            finally:
                if bal is not None:
                    # off-loop like the launch: a wedged balancer's
                    # kill/wait must not stall the churner into session
                    # expiry and poison the direct figures
                    await asyncio.to_thread(_reap, bal)

        stop.set()
        if churn_task.done() and churn_task.exception() is not None:
            # the churner died mid-run: these windows were NOT measured
            # under churn — refuse to publish them as if they were
            raise RuntimeError(
                f"churner failed mid-run: {churn_task.exception()!r}")
        churn_task.cancel()
        out = {"qps": total / elapsed, "p50_us": sorted(p50s)[1],
               "p99_us": max(p99s),
               "qps_spread": round(max(wqps) - min(wqps), 1),
               "mutations": direct_mutations,
               "mutations_per_s": direct_mutations / elapsed}
        if precompile is not None:
            out["precompile"] = precompile
        if mixed_qps is not None:
            out["mixed_qps"] = mixed_qps
            out["mixed_p50_us"] = mixed_p50
            out["mixed_p99_us"] = mixed_p99
        if topo_qps is not None:
            out["topo_qps"] = topo_qps
            out["topo_p99_us"] = topo_p99
        return out
    finally:
        if writer is not None:
            writer.close()
        for p in (srv_proc, zk_proc):
            if p is not None:
                _reap(p)


def _bench_churn(tmpdir: str) -> Dict[str, float]:
    return asyncio.run(_bench_churn_async(tmpdir))


MBALANCER = os.path.join(ROOT, "native", "build", "mbalancer")


N_RECURSION = int(os.environ.get("BENCH_RECURSION_QUERIES", "5000"))
#: distinct dnsblast source addresses for the recursion-heavy axes.
#: Sized so each simulated client stays inside the PRODUCTION
#: per-client recursion burst (100) across a full multi-pass run —
#: the pre-hostile-harness config lift (recursionRate/Burst: 1e9) is
#: gone; these axes now measure forwarding under the shipped limiter.
REC_SOURCES = int(os.environ.get("BENCH_RECURSION_SOURCES", "256"))


def _bench_recursion(tmpdir: str) -> Dict[str, float]:
    """Cross-DC forwarding axis (BASELINE.json proxy config 'recursive
    resolution'): every query misses the local mirror with RD=1 and is
    forwarded to a remote-DC binder on 127.0.0.2 (the self-NIC filter
    covers 127.0.0.1), with answers rebuilt per query and never cached
    (recursion responses carry the do-not-store marker)."""
    remote_fix = {f"/com/bench/remotedc/w{i}": {
        "type": "host", "host": {"address": f"10.20.0.{i + 1}"}}
        for i in range(64)}
    remote_fixture = os.path.join(tmpdir, "remote_fixture.json")
    with open(remote_fixture, "w") as f:
        json.dump(remote_fix, f)
    remote_config = os.path.join(tmpdir, "remote_config.json")
    with open(remote_config, "w") as f:
        json.dump({"dnsDomain": "bench.com",
                   "datacenterName": "remotedc", "host": "127.0.0.2",
                   "store": {"backend": "fake",
                             "fixture": remote_fixture},
                   "queryLog": False}, f)

    local_fixture = os.path.join(tmpdir, "local_empty.json")
    with open(local_fixture, "w") as f:
        json.dump({}, f)

    remote = local = None
    try:
        remote = _launch_server(remote_config)
        rport = wait_for_port(remote)
        local_config = os.path.join(tmpdir, "local_rec_config.json")
        with open(local_config, "w") as f:
            json.dump({"dnsDomain": "bench.com",
                       "datacenterName": "local", "host": "127.0.0.1",
                       "store": {"backend": "fake",
                                 "fixture": local_fixture},
                       "queryLog": False,
                       # PRODUCTION admission limits (no config lift):
                       # the load is spread over REC_SOURCES distinct
                       # source addresses (dnsblast -S), so each
                       # simulated client stays inside the per-client
                       # recursion burst and the axis measures
                       # forwarding under the shipped limiter
                       "recursion": {
                           "dcs": {"remotedc":
                                   [f"127.0.0.2:{rport}"]}}}, f)
        local = _launch_server(local_config)
        port, mport = wait_for_ports(local)

        tmpl = os.path.join(tmpdir, "rec_queries.bin")
        _write_templates(
            tmpl, [(f"w{i}.remotedc.bench.com", Type.A)
                   for i in range(64)], rd=True)

        # readiness probe: forwarding works end to end before timing
        _wait_ready(port, make_query("w0.remotedc.bench.com", Type.A,
                                     qid=1, rd=True).encode(),
                    "recursion path")

        # recursion responses are never cached (do-not-store marker),
        # so repeat passes measure the identical cold forwarding path
        res = _median_passes(
            lambda: _drive_native(port, tmpdir, tmpl_path=tmpl,
                                  n=N_RECURSION, sources=REC_SOURCES),
            N_PASSES)
        # per-stage attribution (VERDICT r5 item 7): scrape the local
        # forwarder's binder_query_stage_seconds so the recursion p50
        # decomposes into splice vs upstream RTT vs event-loop wait —
        # the split covers every timed query of every pass
        try:
            attr = _attribution_from_stages(_scrape_stage_seconds(mport))
            if attr is not None:
                res["attribution"] = attr
        except Exception as e:  # noqa: BLE001 — supplementary figure
            print(f"bench: recursion attribution scrape failed: {e!r}",
                  file=sys.stderr)
        return res
    finally:
        for p in (local, remote):
            if p is not None:
                _reap(p)


def _bench_cross_dc(tmpdir: str) -> Dict[str, object]:
    """Federation axis (ISSUE 11): ONE federated binder whose routing
    table comes from its watched /dcs registry, serving its own
    mirror's names and forwarding names owned by a 'west' DC on
    127.0.0.2 — foreign vs local p50/p99 through the same process.
    Then the whole west DC is killed and the failover convergence is
    measured: elapsed until a foreign name is answered again (stale,
    TTL-clamped) instead of waiting on a dead peer."""
    remote_fix = {f"/com/bench/west/w{i}": {
        "type": "host", "host": {"address": f"10.30.0.{i + 1}",
                                 "ttl": 60}}
        for i in range(64)}
    remote_fixture = os.path.join(tmpdir, "fed_remote_fixture.json")
    with open(remote_fixture, "w") as f:
        json.dump(remote_fix, f)
    remote_config = os.path.join(tmpdir, "fed_remote_config.json")
    with open(remote_config, "w") as f:
        json.dump({"dnsDomain": "bench.com", "datacenterName": "west",
                   "host": "127.0.0.2",
                   "store": {"backend": "fake",
                             "fixture": remote_fixture},
                   "queryLog": False}, f)

    remote = local = None
    try:
        remote = _launch_server(remote_config)
        rport = wait_for_port(remote)

        local_fix = {
            **{f"/com/bench/east/l{i}": {
                "type": "host", "host": {"address": f"10.31.0.{i + 1}",
                                         "ttl": 30}}
               for i in range(64)},
            # DC membership rides the same store the mirror watches
            "/dcs/east": {"zones": ["east"], "peers": []},
            "/dcs/west": {"zones": ["west"],
                          "peers": [f"127.0.0.2:{rport}"]},
        }
        local_fixture = os.path.join(tmpdir, "fed_local_fixture.json")
        with open(local_fixture, "w") as f:
            json.dump(local_fix, f)
        local_config = os.path.join(tmpdir, "fed_local_config.json")
        with open(local_config, "w") as f:
            json.dump({"dnsDomain": "bench.com",
                       "datacenterName": "east", "host": "127.0.0.1",
                       "store": {"backend": "fake",
                                 "fixture": local_fixture},
                       "queryLog": False,
                       # PRODUCTION admission limits: the foreign-name
                       # load runs multi-source (dnsblast -S, see
                       # _bench_recursion) so per-client recursion
                       # limits are honest — no config lift
                       "federation": {"staleTtlClampSeconds": 15}}, f)
        local = _launch_server(local_config)
        port, _mport = wait_for_ports(local)

        ftmpl = os.path.join(tmpdir, "fed_foreign.bin")
        _write_templates(
            ftmpl, [(f"w{i}.west.bench.com", Type.A)
                    for i in range(64)], rd=True)
        ltmpl = os.path.join(tmpdir, "fed_local.bin")
        _write_templates(
            ltmpl, [(f"l{i}.east.bench.com", Type.A)
                    for i in range(64)])

        _wait_ready(port, make_query("w0.west.bench.com", Type.A,
                                     qid=1, rd=True).encode(),
                    "cross-DC forwarding")
        _wait_ready(port, make_query("l0.east.bench.com", Type.A,
                                     qid=1).encode(), "local mirror")

        foreign = _median_passes(
            lambda: _drive_native(port, tmpdir, tmpl_path=ftmpl,
                                  n=N_RECURSION, sources=REC_SOURCES),
            N_PASSES)
        local_res = _median_passes(
            lambda: _drive_native(port, tmpdir, tmpl_path=ltmpl,
                                  n=N_RECURSION), N_PASSES)

        # -- failover convergence: kill the WHOLE west DC, then time
        # until a (cache-warm) foreign name answers NOERROR again —
        # the stale-serve path, measured with fresh one-shot sockets
        # so a dead-peer wait shows up as elapsed time, not a hang
        _reap(remote)
        remote = None
        probe = make_query("w1.west.bench.com", Type.A, qid=2,
                           rd=True).encode()
        start = time.time()
        deadline = start + 30.0
        convergence_ms = None
        while time.time() < deadline:
            s = _socket_mod.socket(_socket_mod.AF_INET,
                                   _socket_mod.SOCK_DGRAM)
            s.settimeout(1.0)
            s.connect(("127.0.0.1", port))
            try:
                s.send(probe)
                resp = s.recv(512)
                if not (resp[3] & 0x0F) and resp[6:8] != b"\x00\x00":
                    convergence_ms = (time.time() - start) * 1e3
                    break
            except _socket_mod.timeout:
                pass
            finally:
                s.close()
        if convergence_ms is None:
            raise RuntimeError("foreign names never converged to "
                               "stale serving after DC loss")
        return {
            "foreign_qps": round(foreign["qps"], 1),
            "foreign_qps_spread": foreign.get("qps_spread"),
            "foreign_p50_us": round(foreign["p50_us"], 1),
            "foreign_p99_us": round(foreign["p99_us"], 1),
            "local_qps": round(local_res["qps"], 1),
            "local_qps_spread": local_res.get("qps_spread"),
            "local_p50_us": round(local_res["p50_us"], 1),
            "local_p99_us": round(local_res["p99_us"], 1),
            "failover_convergence_ms": round(convergence_ms, 1),
            "passes": foreign["passes"],
        }
    finally:
        for p in (local, remote):
            if p is not None:
                _reap(p)


N_REALISTIC = int(os.environ.get("BENCH_REALISTIC_QUERIES",
                                 str(N_QUERIES)))


async def _bench_realistic_async(tmpdir: str) -> Dict[str, object]:
    """The combined realistic-posture axis (round-5 VERDICT ask): every
    adverse production condition AT ONCE — per-query logging on (the
    reference's unconditional posture), TCP clients pipelining alongside
    the UDP flood, sustained store churn through the real ZooKeeper wire
    protocol, and a recursion slice (RD forwards to a remote-DC binder)
    mixed into the load.  One number, `realistic_qps`, for what an
    operator actually gets when nothing is idealized; the per-transport
    splits, churn rate, recursion share, log-line count, and the
    precompile economics ride along so a movement is attributable."""
    from binder_tpu.store.zk_client import ZKClient

    # remote-DC binder on 127.0.0.2 for the recursion slice
    remote_fix = {f"/com/bench/remotedc/r{i}": {
        "type": "host", "host": {"address": f"10.40.0.{i + 1}"}}
        for i in range(16)}
    remote_fixture = os.path.join(tmpdir, "real_remote_fixture.json")
    with open(remote_fixture, "w") as f:
        json.dump(remote_fix, f)
    remote_config = os.path.join(tmpdir, "real_remote_config.json")
    with open(remote_config, "w") as f:
        json.dump({"dnsDomain": "bench.com",
                   "datacenterName": "remotedc", "host": "127.0.0.2",
                   "store": {"backend": "fake",
                             "fixture": remote_fixture},
                   "queryLog": False}, f)

    zk_proc = subprocess.Popen(
        _pin("server")
        + [sys.executable, "-u", "-m", "binder_tpu.store.zk_testserver",
           "0"],
        cwd=ROOT, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=_bench_env())
    remote = srv_proc = None
    writer = None
    logf = None
    logpath = os.path.join(tmpdir, "realistic.out")
    try:
        remote = _launch_server(remote_config)
        rport = wait_for_port(remote)
        zk_port = _wait_for_line(
            zk_proc, rb"listening on 127\.0\.0\.1:(\d+)\n",
            "zk-testserver")

        writer = ZKClient(address="127.0.0.1", port=zk_port)
        writer.start()
        deadline = time.time() + 10
        while not writer.is_connected():
            if time.time() > deadline:
                raise RuntimeError("zk seed client did not connect")
            await asyncio.sleep(0.02)
        for path, obj in FIXTURE.items():
            await writer.mkdirp(path, json.dumps(obj).encode())
        for i in range(N_CHURN_HOSTS):
            await writer.mkdirp(
                f"/com/bench/rchurn{i}",
                json.dumps({"type": "host",
                            "host": {"address": f"10.41.0.{i + 1}"}}
                           ).encode())

        config = os.path.join(tmpdir, "realistic_config.json")
        with open(config, "w") as f:
            json.dump({"dnsDomain": "bench.com", "datacenterName": "dc0",
                       "host": "127.0.0.1",
                       "store": {"backend": "zookeeper",
                                 "host": "127.0.0.1", "port": zk_port},
                       "queryLog": True,
                       # PRODUCTION admission limits — no config lift.
                       # The UDP leg runs multi-source (dnsblast -S);
                       # the TCP leg's small recursion share stays
                       # inside one client's budget or gets the
                       # limiter's REFUSED, which IS the realistic
                       # posture (recorded via the shed scrape below).
                       "recursion": {
                           "dcs": {"remotedc":
                                   [f"127.0.0.2:{rport}"]}}}, f)
        logf = open(logpath, "wb")
        srv_proc = subprocess.Popen(
            _pin("server")
            + [sys.executable, "-u", "-m", "binder_tpu.main", "-f",
               config, "-p", "0"],
            cwd=ROOT, env=_bench_env(), stdout=logf,
            stderr=subprocess.DEVNULL)
        port = _wait_for_file_line(
            logpath, rb"UDP DNS service started on [\d.]+:(\d+)\"",
            "realistic bench server", srv_proc)
        mport = _wait_for_file_line(
            logpath, rb"metrics server started on port (\d+)\"",
            "realistic bench server", srv_proc)

        await asyncio.to_thread(
            _wait_ready, port, make_query(*BENCH_MIX[0], qid=1).encode(),
            "realistic server over zk")
        await asyncio.to_thread(
            _wait_ready, port,
            make_query("r0.remotedc.bench.com", Type.A, qid=2,
                       rd=True).encode(),
            "realistic recursion path")

        # query mix: 3 cycles of the hot mix + 1 RD remote name per 13
        # (≈7.7% recursion share — cross-DC forwards are RTT-bound and
        # would otherwise own the whole figure)
        tmpl = os.path.join(tmpdir, "realistic_queries.bin")
        with open(tmpl, "wb") as f:
            for _ in range(3):
                for name, qtype in BENCH_MIX:
                    wire = make_query(name, qtype, qid=0).encode()
                    f.write(len(wire).to_bytes(2, "big") + wire)
            wire = make_query("r0.remotedc.bench.com", Type.A, qid=0,
                              rd=True).encode()
            f.write(len(wire).to_bytes(2, "big") + wire)

        mutations = 0
        stop = asyncio.Event()

        async def churner():
            nonlocal mutations
            i = 0
            while not stop.is_set():
                i += 1
                await writer.set_data(
                    f"/com/bench/rchurn{i % N_CHURN_HOSTS}",
                    json.dumps({"type": "host",
                                "host": {"address":
                                         f"10.42.{i % 250}.{i % 250 + 1}"
                                         }}).encode())
                mutations += 1
                await asyncio.sleep(CHURN_INTERVAL_S)

        churn_task = asyncio.ensure_future(churner())
        n_udp = N_REALISTIC
        n_tcp = max(N_REALISTIC // 2, 1)

        async def blast(mode_args, n):
            if not mode_args:   # UDP leg: spread the client population
                mode_args = ["-S", str(REC_SOURCES)]
            proc = await asyncio.create_subprocess_exec(
                *_pin("client"), DNSBLAST, "-p", str(port),
                "-n", str(n), "-w", str(CONCURRENCY), "-t", tmpl,
                *mode_args,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL)
            out, _ = await proc.communicate()
            if proc.returncode != 0:
                raise RuntimeError(
                    f"dnsblast failed on the realistic axis "
                    f"({mode_args or 'udp'})")
            return json.loads(out)

        t0 = time.perf_counter()
        udp_res, tcp_res = await asyncio.gather(
            blast([], n_udp), blast(["-m", "tcp", "-T", "8"], n_tcp))
        elapsed = time.perf_counter() - t0
        stop.set()
        if churn_task.done() and churn_task.exception() is not None:
            raise RuntimeError(
                f"churner failed mid-run: {churn_task.exception()!r}")
        churn_task.cancel()

        precompile = None
        try:
            precompile = _scrape_precompile(mport)
        except Exception as e:  # noqa: BLE001 — supplementary figure
            print(f"bench: realistic precompile scrape failed: {e!r}",
                  file=sys.stderr)

        # under production admission limits, sheds are part of the
        # posture — record the split so errors are attributable
        shed = None
        try:
            shed = _scrape_shed(mport)
        except Exception as e:  # noqa: BLE001 — supplementary figure
            print(f"bench: realistic shed scrape failed: {e!r}",
                  file=sys.stderr)

        out = {
            "qps": (n_udp + n_tcp) / elapsed,
            "p50_us": max(udp_res["p50_us"], tcp_res["p50_us"]),
            "p99_us": max(udp_res["p99_us"], tcp_res["p99_us"]),
            "udp_qps": udp_res["qps"], "tcp_qps": tcp_res["qps"],
            "errors": udp_res.get("errors", 0)
            + tcp_res.get("errors", 0),
            "mutations_per_s": mutations / elapsed,
            "recursion_share": 1.0 / 13.0,
        }
        if precompile is not None:
            out["precompile"] = precompile
        if shed:
            out["shed"] = shed
        return out
    finally:
        if writer is not None:
            writer.close()
        for p in (srv_proc, remote, zk_proc):
            if p is not None:
                _reap(p)
        if logf is not None:
            logf.close()


def _bench_realistic(tmpdir: str) -> Dict[str, object]:
    res = asyncio.run(_bench_realistic_async(tmpdir))
    # every-query-leaves-a-record, load-verified like the logged axis
    # (counted after the server exited and its log stream flushed)
    n_lines = 0
    try:
        with open(os.path.join(tmpdir, "realistic.out"), "rb") as f:
            for ln in f:
                if b'"DNS query"' in ln:
                    n_lines += 1
    except OSError:
        pass
    res["log_lines"] = n_lines
    return res


def _launch_balancer(sockdir: str, extra_args: List[str] = ()):
    """Start mbalancer on an ephemeral port fronting `sockdir`; returns
    (proc, port).  Shared by the topology and balancer-churn axes so
    both measure an identically configured balancer.  stderr goes to a
    file beside the sockets so a startup death is diagnosable (it has
    been observed transiently under full-bench load) without risking a
    blocking pipe mid-run."""
    errpath = os.path.join(sockdir, ".balancer.stderr")
    with open(errpath, "wb") as errf:
        bal = subprocess.Popen(
            _pin("server")
            + [MBALANCER, "-d", sockdir, "-p", "0", "-b", "127.0.0.1",
               "-s", "300"] + list(extra_args),
            stdout=subprocess.PIPE, stderr=errf)
    try:
        port = _wait_for_line(bal, rb"PORT (\d+)\n", "mbalancer")
    except Exception as e:
        _reap(bal)
        try:
            with open(errpath, "rb") as f:
                tail = f.read()[-400:].decode("utf-8", "replace")
        except OSError:
            tail = ""
        raise RuntimeError(f"{e}; mbalancer stderr: {tail!r}") from e
    return bal, port


def _bench_topology(tmpdir: str, n_backends: int = 2,
                    tag: str = "") -> Dict[str, float]:
    """Deployment-shape measurement: mbalancer fronting `n_backends`
    over the balancer socket protocol, driven with the same query mix.
    One warm-up pass, then median of N_PASSES with spread; the
    balancer's per-stage counters (cache hit rate, forward RTT, write
    queue high-water) ride along so a cross-round delta on this axis
    can be attributed to a stage instead of bisected blind."""
    # unique per attempt: the axis retry (_try_axis) must not die on a
    # directory a failed first attempt left behind
    sockdir = tempfile.mkdtemp(dir=tmpdir, prefix=f"vsock{tag}")
    fixture = os.path.join(tmpdir, "fixture.json")
    if not os.path.exists(fixture):
        with open(fixture, "w") as f:
            json.dump(FIXTURE, f)

    procs = []   # every child, reaped on any exit path
    try:
        for i in range(n_backends):
            config = os.path.join(tmpdir, f"bconfig{tag}{i}.json")
            with open(config, "w") as f:
                json.dump({
                    "dnsDomain": "bench.com", "datacenterName": "dc0",
                    "host": "127.0.0.1",
                    "store": {"backend": "fake", "fixture": fixture},
                    "queryLog": False,
                    "balancerSocket": os.path.join(sockdir, str(i)),
                }, f)
            p = _launch_server(config)
            procs.append(p)
            wait_for_port(p)
        bal, port = _launch_balancer(sockdir)
        procs.append(bal)
        time.sleep(0.5)   # backend scan + connect
        _drive_native(port, tmpdir)          # warm the balancer cache
        res = _median_passes(lambda: _drive_native(port, tmpdir),
                             N_PASSES)
        try:
            stats = _read_balancer_stats(sockdir)
            served = stats.get("cache_hits", 0) + \
                stats.get("cache_misses", 0) + stats.get("uncacheable", 0)
            res["cache_hit_pct"] = round(
                100.0 * stats.get("cache_hits", 0) / served, 1) \
                if served else None
            res["fwd_rtt_p99_us"] = _rtt_p99_us(stats)
            res["backend_wq_peak"] = stats.get("backend_wq_peak")
            # stage_cycles decomposition (VERDICT r5 item 6): which
            # stage of the balancer's own packet path owns the fronting
            # overhead, so a cross-round overhead swing is attributable
            res["attribution"] = _balancer_attribution(stats)
        except (OSError, ValueError) as e:
            print(f"bench: balancer stats read failed: {e!r}",
                  file=sys.stderr)
        return res
    finally:
        for p in reversed(procs):   # balancer first, then backends
            _reap(p)


def _bench_balancer_overhead(tmpdir: str) -> Dict[str, object]:
    """Balancer-overhead isolation, interleaved A/B.  One backend served
    DIRECT and one identical backend FRONTED by mbalancer, both alive at
    once, driven in alternating A-B-A-B passes inside one time window —
    the r5 headline-ledger discipline applied within a single run.  The
    previous scheme compared the fronted figure against the headline
    axis measured minutes earlier, so any box drift between those two
    points landed wholesale in the overhead estimate (the recorded
    swings: 7.7% → 15.6% → −31.6% at an essentially unchanged fronted
    qps).  Interleaving makes drift cancel: both sides see the same
    thermal/scheduler environment pass by pass, and two consecutive
    full runs agree on the overhead within noise.  The balancer's
    stage_cycles attribution rides along so the overhead has an owning
    stage, not just a magnitude.

    The balancer runs with its answer cache OFF (-c 0): with the
    default warm cache the axis measures the cache (which serves
    repeats without a backend round trip and reads FASTER than direct,
    overhead ≈ −10%) plus its hit-rate nondeterminism; with it off,
    every query takes the full client→balancer→backend path, which is
    the packet-path overhead the axis exists to isolate (the cached
    posture's throughput is the topology axis's job).

    ISSUE 18 widened the A/B to an A/B/C: the fronted arm runs with
    direct return (the backend answers on the balancer's passed UDP
    socket, replies never re-enter the balancer) and a second
    relay-pinned balancer (`-D`) fronts an identical backend in the
    same interleaved window — so the direct-return win is measured
    against both the no-balancer baseline and the classic relay under
    one thermal/scheduler environment.  Each balancer arm also reports
    `syscalls_per_query` (packet-path syscalls over queries — the
    floor the direct-return path exists to lower) and the recvmmsg
    `udp_batch_cells` histogram (mass above cell 0 proves the client
    socket drains in batches)."""
    sockdir = tempfile.mkdtemp(dir=tmpdir, prefix="vsockab")
    rsockdir = tempfile.mkdtemp(dir=tmpdir, prefix="vsockrl")
    fixture = os.path.join(tmpdir, "fixture.json")
    if not os.path.exists(fixture):
        with open(fixture, "w") as f:
            json.dump(FIXTURE, f)
    rounds = max(3, N_PASSES)
    procs = []   # every child, reaped on any exit path
    try:
        base = {"dnsDomain": "bench.com", "datacenterName": "dc0",
                "host": "127.0.0.1", "queryLog": False,
                "store": {"backend": "fake", "fixture": fixture}}
        dconfig = os.path.join(tmpdir, "abdirect.json")
        with open(dconfig, "w") as f:
            json.dump(base, f)
        direct = _launch_server(dconfig)
        procs.append(direct)
        dport = wait_for_port(direct)

        fconfig = os.path.join(tmpdir, "abfronted.json")
        with open(fconfig, "w") as f:
            json.dump({**base,
                       "balancerSocket": os.path.join(sockdir, "0")}, f)
        backend = _launch_server(fconfig)
        procs.append(backend)
        wait_for_port(backend)
        bal, fport = _launch_balancer(sockdir, ["-c", "0"])
        procs.append(bal)

        rconfig = os.path.join(tmpdir, "abrelay.json")
        with open(rconfig, "w") as f:
            json.dump({**base,
                       "balancerSocket": os.path.join(rsockdir, "0")}, f)
        rbackend = _launch_server(rconfig)
        procs.append(rbackend)
        wait_for_port(rbackend)
        rbal, rport = _launch_balancer(rsockdir, ["-c", "0", "-D"])
        procs.append(rbal)
        time.sleep(0.5)   # backend scan + connect

        _drive_native(dport, tmpdir)   # warm all three arms
        _drive_native(fport, tmpdir)
        _drive_native(rport, tmpdir)
        dpasses: List[Dict[str, float]] = []
        fpasses: List[Dict[str, float]] = []
        rpasses: List[Dict[str, float]] = []
        for _ in range(rounds):
            dpasses.append(_drive_native(dport, tmpdir))
            fpasses.append(_drive_native(fport, tmpdir))
            rpasses.append(_drive_native(rport, tmpdir))

        def med(passes):
            passes = sorted(passes, key=lambda r: r["qps"])
            r = dict(passes[len(passes) // 2])
            r["qps_spread"] = round(
                passes[-1]["qps"] - passes[0]["qps"], 1)
            return r

        dres, fres, rres = med(dpasses), med(fpasses), med(rpasses)
        out: Dict[str, object] = {
            "direct_qps": round(dres["qps"], 1),
            "direct_qps_spread": dres["qps_spread"],
            "fronted_qps": round(fres["qps"], 1),
            "fronted_qps_spread": fres["qps_spread"],
            "relay_qps": round(rres["qps"], 1),
            "relay_qps_spread": rres["qps_spread"],
            "overhead_pct": round(
                (1.0 - fres["qps"] / dres["qps"]) * 100.0, 1),
            "relay_overhead_pct": round(
                (1.0 - rres["qps"] / dres["qps"]) * 100.0, 1),
            "passes": rounds,
        }

        def bal_block(sdir):
            stats = _read_balancer_stats(sdir)
            queries = (stats.get("udp_queries", 0)
                       + stats.get("tcp_queries", 0))
            block = {
                # the per-query syscall floor — acceptance wants
                # <= 0.5 on the direct-return path (batching amortizes
                # one recvmmsg+sendmmsg pair over up to 128 queries,
                # and replies never transit the balancer at all)
                "syscalls_per_query": round(
                    stats.get("syscalls", 0) / queries, 3)
                if queries else None,
                "udp_batch_cells": stats.get("udp_batch_cells"),
                "direct_return": stats.get("direct_return"),
                "fd_passes": stats.get("fd_passes"),
                "direct_forwards": stats.get("direct_forwards"),
                # stage_cycles decomposition (VERDICT r5 item 6): which
                # stage of the balancer's own packet path owns the
                # overhead — reply-relay should collapse on the
                # direct-return arm
                "attribution": _balancer_attribution(stats),
            }
            return block
        try:
            out["fronted"] = bal_block(sockdir)
            out["attribution"] = out["fronted"]["attribution"]
        except (OSError, ValueError) as e:
            print(f"bench: balancer stats read failed: {e!r}",
                  file=sys.stderr)
        try:
            out["relay"] = bal_block(rsockdir)
        except (OSError, ValueError) as e:
            print(f"bench: relay balancer stats read failed: {e!r}",
                  file=sys.stderr)
        return out
    finally:
        for p in reversed(procs):   # balancer first, then backends
            _reap(p)


N_DEGRADED = int(os.environ.get("BENCH_DEGRADED_QUERIES",
                                str(min(20000, N_QUERIES))))


def _scrape_gauge(metrics_port: int, name: str) -> Optional[float]:
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{metrics_port}/metrics",
                timeout=5) as r:
            text = r.read().decode()
    except OSError:
        return None
    m = re.search(rf"^{re.escape(name)}(?:\{{[^}}]*\}})? ([0-9.eE+-]+)$",
                  text, re.M)
    return float(m.group(1)) if m else None


def _bench_degraded(tmpdir: str) -> Dict[str, object]:
    """Degradation axis (`--chaos` posture, ISSUE 4): the SAME hot
    host-A mix served in the three policy states, with the server's
    own `chaos` config block scripting the session loss in-process
    (docs/degradation.md):

    - `degraded_qps` — **stale-serving**: session killed at start,
      cap effectively infinite; every answer rides the generic path
      with TTL clamping (the raw lane and native fast path stand down
      when degraded), so this figure is the honest cost of degraded
      serving vs the fresh headline;
    - `withheld_qps` — **stale-exhausted**: cap ~0; every query gets
      an immediate well-formed SERVFAIL — the refusal throughput
      under total store loss (a hang or timeout here would tank the
      figure; the bound IS the property);
    - scrape-asserted: `binder_degraded_state` reads 1 / 2 in the
      respective phases and the stale counters advance — the axis
      fails rather than silently measuring the wrong state."""
    fix = {f"/com/bench/w{i}": {
        "type": "host", "host": {"address": f"10.30.0.{i + 1}"}}
        for i in range(64)}
    fixture = os.path.join(tmpdir, "degraded_fixture.json")
    with open(fixture, "w") as f:
        json.dump(fix, f)
    tmpl = os.path.join(tmpdir, "degraded_queries.bin")
    _write_templates(tmpl, [(f"w{i}.bench.com", Type.A)
                            for i in range(64)])
    probe = make_query("w0.bench.com", Type.A, qid=1).encode()

    def phase(tag: str, max_staleness: float,
              want_state: float) -> Dict[str, float]:
        config = os.path.join(tmpdir, f"degraded_config_{tag}.json")
        with open(config, "w") as f:
            json.dump({
                "dnsDomain": "bench.com", "datacenterName": "dc0",
                "host": "127.0.0.1",
                "store": {"backend": "fake", "fixture": fixture},
                "queryLog": False,
                "degradation": {"maxStalenessSeconds": max_staleness,
                                "staleTtlClampSeconds": 5},
                "chaos": {"plan": "at 0.0 lose-session"},
            }, f)
        proc = _launch_server(config)
        try:
            port, mport = wait_for_ports(proc)
            if want_state < 2:
                _wait_ready(port, probe, f"degraded axis ({tag})")
            # the scripted session loss must have landed (and, for the
            # exhausted phase, aged past the cap) before measuring
            deadline = time.time() + 15
            while time.time() < deadline:
                if _scrape_gauge(mport, "binder_degraded_state") \
                        == want_state:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError(
                    f"degraded axis: state never reached {want_state}")
            res = _median_passes(
                lambda: _drive_native(port, tmpdir, tmpl_path=tmpl,
                                      n=N_DEGRADED), N_PASSES)
            if _scrape_gauge(mport, "binder_degraded_state") \
                    != want_state:
                raise RuntimeError(
                    f"degraded axis: state drifted mid-measurement "
                    f"({tag})")
            res["stale_served"] = _scrape_gauge(
                mport, "binder_stale_served_total")
            res["withheld"] = _scrape_gauge(
                mport, "binder_stale_withheld_total")
            return res
        finally:
            _reap(proc)

    stale = phase("stale", 86400.0, 1.0)
    exhausted = phase("exhausted", 0.05, 2.0)
    if not stale.get("stale_served"):
        raise RuntimeError("degraded axis measured zero stale serves")
    if exhausted["errors"] < N_DEGRADED:
        raise RuntimeError("exhausted phase served data answers")
    return {
        "qps": stale["qps"], "qps_spread": stale.get("qps_spread"),
        "p50_us": stale["p50_us"], "p99_us": stale["p99_us"],
        "withheld_qps": exhausted["qps"],
        "withheld_p99_us": exhausted["p99_us"],
        "queries": N_DEGRADED,
    }


#: shard worker counts the shard axis measures (comma-separated env
#: override; `make bench-smoke` trims it to keep CI fast)
SHARD_NS = [int(x) for x in os.environ.get(
    "BENCH_SHARD_NS", "1,2,4").split(",") if x.strip()]
#: concurrent load-generator processes for the shard axis: SO_REUSEPORT
#: balances by 4-tuple hash, so ONE client socket would land every
#: query on one worker — distinct source sockets are what make the
#: kernel spread.  Balance is flow-granular (each client is ONE flow),
#: so enough flows are needed for the distribution figure to mean
#: anything: with 16 flows over 4 shards, an empty shard is ~4%
#: probable by chance; with 4 flows it was ~12% probable over TWO.
SHARD_CLIENTS = int(os.environ.get("BENCH_SHARD_CLIENTS", "16"))


def _drive_native_shard(port: int, tmpl_path: str,
                        n_total: int) -> Dict[str, float]:
    """SHARD_CLIENTS concurrent dnsblast processes against one port.

    Aggregate qps is total-queries / wall-clock of the whole batch (the
    slowest client closes the window — summing per-process qps would
    overcount when finish times skew).  p50 is the median of the
    per-process medians; p99 the worst process's p99 (conservative)."""
    per = max(1, n_total // SHARD_CLIENTS)
    t0 = time.perf_counter()
    procs = [subprocess.Popen(
        _pin("client")
        + [DNSBLAST, "-p", str(port), "-n", str(per),
           "-w", str(max(8, CONCURRENCY // SHARD_CLIENTS)),
           "-t", tmpl_path],
        stdout=subprocess.PIPE, text=True)
        for _ in range(SHARD_CLIENTS)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=330)
            if p.returncode:
                raise RuntimeError(
                    f"dnsblast exited {p.returncode} on shard axis")
            outs.append(json.loads(out))
    finally:
        for p in procs:
            _reap(p)
    elapsed = time.perf_counter() - t0
    p50s = sorted(o["p50_us"] for o in outs)
    return {
        "qps": per * SHARD_CLIENTS / elapsed,
        "p50_us": p50s[len(p50s) // 2],
        "p99_us": max(o["p99_us"] for o in outs),
        "errors": sum(o.get("errors", 0) for o in outs),
        "client_procs": SHARD_CLIENTS,
    }


def _shard_status(mport: int) -> Dict[str, object]:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/status", timeout=5) as r:
        return json.loads(r.read())


def _bench_shard(tmpdir: str) -> Dict[str, object]:
    """Shard axis (ISSUE 6): `shard_qps` at N=1/2/4 worker processes
    behind one kernel-balanced SO_REUSEPORT port, with:

    - an in-process control (`inproc_qps`) measured with the SAME
      multi-process client topology, so `shard-mode overhead at N=1`
      is a like-for-like ratio (the headline axis uses one client and
      is not comparable);
    - scaling efficiency vs ideal = min(N, cores) — on the 1-core dev
      VM ideal is 1 and the honest pass is mechanism + overhead; on
      multi-core hardware the same figure is the scaling headline;
    - per-shard query-distribution balance (min/max share of the
      `binder_shard_requests` fold) proving the kernel actually
      spread the load;
    - shard PIDs recorded so the "N distinct processes" claim is
      checkable in the JSON."""
    fixture = os.path.join(tmpdir, "shard_fixture.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    tmpl = os.path.join(tmpdir, "shard_queries.bin")
    _write_templates(tmpl, BENCH_MIX)

    def boot(shards: int):
        config = os.path.join(tmpdir, f"shard_config_{shards}.json")
        with open(config, "w") as f:
            json.dump({
                "dnsDomain": "bench.com", "datacenterName": "dc0",
                "host": "127.0.0.1",
                "store": {"backend": "fake", "fixture": fixture},
                "queryLog": False,
                **({"shards": shards} if shards else {}),
            }, f)
        return _launch_server(config)

    out: Dict[str, object] = {"ns": SHARD_NS, "clients": SHARD_CLIENTS,
                              "cores": NPROC, "qps": {}, "p50_us": {},
                              "p99_us": {}, "qps_spread": {},
                              "pids": {}, "balance": {}}
    # in-process control: same stack, no supervisor, same client shape
    proc = boot(0)
    try:
        port, _ = wait_for_ports(proc)
        ctl = _median_passes(
            lambda: _drive_native_shard(port, tmpl, N_QUERIES),
            N_PASSES)
        out["inproc_qps"] = round(ctl["qps"], 1)
        out["inproc_qps_spread"] = ctl.get("qps_spread")
    finally:
        _reap(proc)

    for n in SHARD_NS:
        proc = boot(n)
        try:
            port, mport = wait_for_ports(proc)
            res = _median_passes(
                lambda: _drive_native_shard(port, tmpl, N_QUERIES),
                N_PASSES)
            key = str(n)
            out["qps"][key] = round(res["qps"], 1)
            out["qps_spread"][key] = res.get("qps_spread")
            out["p50_us"][key] = round(res["p50_us"], 1)
            out["p99_us"][key] = round(res["p99_us"], 1)
            # let the final 1 Hz stats frames fold before reading the
            # per-shard distribution
            time.sleep(2.0)
            snap = _shard_status(mport)
            workers = snap["shards"]["workers"]
            out["pids"][key] = [w["pid"] for w in workers]
            reqs = [float(w["requests"]) for w in workers]
            if n > 1 and sum(reqs) > 0:
                shares = [r / sum(reqs) for r in reqs]
                # 1.0 = perfectly even; 0 = one shard took everything
                out["balance"][key] = round(
                    min(shares) / max(shares), 3)
        finally:
            _reap(proc)

    base = out["qps"].get("1")
    if base:
        out["efficiency"] = {
            str(n): round(out["qps"][str(n)]
                          / (base * min(n, NPROC)), 3)
            for n in SHARD_NS if str(n) in out["qps"]}
        out["shard1_overhead_pct"] = round(
            (1.0 - base / out["inproc_qps"]) * 100.0, 1)
    return out


# -- zone_scale axis (ISSUE 7): the headline numbers at production ----
# -- zone sizes, with the 100-name figure as the control ----
#
# Two phases per size.  Phase A is tools/zone_probe.py in a SUBPROCESS
# (mirror build time / RSS-per-name / single-name mutation latency /
# watch-storm recovery / chunked-rebuild loop lag, each measured in a
# pristine address space so sizes never pollute each other's RSS).
# Phase B boots a real server on a synthetic zone of that size and
# drives the standard headline mix — steady-state qps as a function of
# zone scale, same client, same mix.

ZONE_SIZES = os.environ.get("BENCH_ZONE_SIZES",
                            "100,10000,100000,1000000")
N_ZONE = int(os.environ.get("BENCH_ZONE_QUERIES", "30000"))

#: the dict-per-node representation this round replaced, measured at
#: 100k names on this box immediately before the refactor (see
#: docs/bench.md round-10 for provenance) — the comparator for the
#: rss_per_name_vs_legacy ratio
LEGACY_RSS_PER_NAME_BYTES = 2077.0


def _proc_busy_fraction(pid: int, interval: float) -> float:
    """CPU busy fraction of `pid` over `interval` seconds (utime+stime
    from /proc)."""
    def ticks() -> int:
        with open(f"/proc/{pid}/stat") as f:
            parts = f.read().rsplit(") ", 1)[1].split()
        return int(parts[11]) + int(parts[12])
    try:
        t0 = ticks()
        time.sleep(interval)
        t1 = ticks()
    except (OSError, IndexError, ValueError):
        return 0.0
    hz = os.sysconf("SC_CLK_TCK")
    return (t1 - t0) / hz / interval


def _zone_scale_probe(n: int) -> Dict[str, object]:
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "zone_probe.py"),
         str(n), "150", str(max(500, min(5000, n // 10)))],
        capture_output=True, text=True, check=True,
        timeout=600 + n // 2000)
    return json.loads(out.stdout)


def _zone_scale_qps(tmpdir: str, n: int) -> Dict[str, float]:
    fixture = os.path.join(tmpdir, "fixture.json")
    config = os.path.join(tmpdir, f"zone{n}.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    with open(config, "w") as f:
        json.dump({
            "dnsDomain": "bench.com", "datacenterName": "dc0",
            "host": "127.0.0.1",
            "store": {"backend": "fake", "fixture": fixture,
                      "synthetic": {"hosts": n}},
            "queryLog": False,
        }, f)
    proc = _launch_server(config)
    try:
        # mirror build is part of boot: scale the deadline with n
        port, buf = _wait_for_line_buf(
            proc, rb"UDP DNS service started on [\d.]+:(\d+)\"",
            "bench server", timeout=30.0 + n / 10000.0)
        m = re.search(rb"metrics server started on port (\d+)\"", buf)
        mport = int(m.group(1)) if m else None
        # steady state, not warm-up: at zone scale the precompile seed
        # and zone fill stream in the background after serving starts
        # (by design); wait for the seed to land AND the server to go
        # CPU-idle (the zone fill has no scrapeable progress counter —
        # idleness covers every background walk at once)
        if mport is not None:
            deadline = time.time() + 60.0 + n / 4000.0
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{mport}/status",
                            timeout=5) as resp:
                        snap = json.loads(resp.read())
                    pc = snap.get("precompile")
                    if pc is None or pc.get("seed_remaining", 0) == 0:
                        break
                except OSError:
                    pass
                time.sleep(0.25)
            while time.time() < deadline:
                if _proc_busy_fraction(proc.pid, 0.5) < 0.25:
                    break
        res = _median_passes(
            lambda: _drive_native(port, tmpdir, n=N_ZONE), 3)
    finally:
        _reap(proc)
    return res


def _bench_zone_scale(tmpdir: str) -> Dict[str, object]:
    sizes = [int(s) for s in ZONE_SIZES.split(",") if s.strip()]
    per_size: Dict[str, dict] = {}
    control_qps = None
    for n in sizes:
        entry: Dict[str, object] = {}
        probe = _zone_scale_probe(n)
        entry["probe"] = probe
        qps = _zone_scale_qps(tmpdir, n)
        entry["qps"] = round(qps["qps"], 1)
        entry["qps_spread"] = qps.get("qps_spread")
        entry["p50_us"] = round(qps["p50_us"], 1)
        entry["p99_us"] = round(qps["p99_us"], 1)
        if control_qps is None:
            control_qps = qps["qps"]
        entry["qps_vs_control"] = round(qps["qps"] / control_qps, 3)
        per_size[str(n)] = entry
    largest = per_size[str(sizes[-1])]
    smallest_probe = per_size[str(sizes[1])]["probe"] \
        if len(sizes) > 1 else largest["probe"]
    rss = largest["probe"]["mirror_rss_per_name_bytes"]
    return {
        "sizes": sizes,
        "per_size": per_size,
        # the acceptance headlines, precomputed so the JSON answers
        # them without arithmetic
        "rss_per_name_bytes": rss,
        "legacy_rss_per_name_bytes": LEGACY_RSS_PER_NAME_BYTES,
        "rss_per_name_vs_legacy": round(
            LEGACY_RSS_PER_NAME_BYTES / rss, 2) if rss else None,
        "mutation_p50_us_largest":
            largest["probe"]["mutation_p50_us"],
        "mutation_flatness": round(
            largest["probe"]["mutation_p50_us"]
            / smallest_probe["mutation_p50_us"], 2),
        "qps_largest_vs_control": largest["qps_vs_control"],
        "rebuild_max_loop_lag_ms_largest":
            largest["probe"]["rebuild_max_loop_lag_ms"],
        "parity_failures": sum(
            e["probe"]["parity_failures"] for e in per_size.values()),
    }


N_HOSTILE_SECONDS = float(os.environ.get("BENCH_HOSTILE_SECONDS", "15"))
HOSTILE_QPS = int(os.environ.get("BENCH_HOSTILE_QPS", "6000"))
HOSTILE_FLOWS = int(os.environ.get("BENCH_HOSTILE_FLOWS", "64"))
#: paced legit offered load for the goodput measurement — must sit
#: under the production RRL per-prefix limit (200 rps), or the probe
#: measures its own rate limiting instead of the flood's collateral
HOSTILE_LEGIT_QPS = int(os.environ.get("BENCH_HOSTILE_LEGIT_QPS", "150"))


def _bench_hostile(tmpdir: str) -> Dict[str, object]:
    """Hostile-internet axis (ISSUE 12): legit goodput under an
    adversarial multi-flow flood (tools/hostile.py — spoofed-source
    prefixes, malformed/EDNS/oversized frames, cache-missing names)
    against the same server config the headline axes use PLUS the
    production RRL block.  Records the no-flood control, the
    under-flood goodput, their ratio (acceptance: >= 0.8), and the
    server-side shed/slip attribution scraped from `binder_rrl_*` /
    `binder_shed_total` — so "binder survives the open internet" is a
    measured figure, not a claim."""
    from tools.hostile import DEFAULT_MIX, legit_probe

    fixture = os.path.join(tmpdir, "hostile_fixture.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    config = os.path.join(tmpdir, "hostile_config.json")
    with open(config, "w") as f:
        json.dump({"dnsDomain": "bench.com", "datacenterName": "dc0",
                   "host": "127.0.0.1",
                   "store": {"backend": "fake", "fixture": fixture},
                   "queryLog": False,
                   # production RRL posture (etc/config.json defaults)
                   "rrl": {}}, f)
    names = ["web.bench.com", "svc.bench.com"]
    proc = _launch_server(config)
    flood = None
    try:
        port, mport = wait_for_ports(proc)
        control = legit_probe("127.0.0.1", port,
                              duration=max(3.0, N_HOSTILE_SECONDS / 3),
                              names=names, qps=HOSTILE_LEGIT_QPS)
        if not control["answered"]:
            raise RuntimeError("hostile axis: control probe unanswered")
        flood = subprocess.Popen(
            _pin("client")
            + [sys.executable, "-u",
               os.path.join(ROOT, "tools", "hostile.py"),
               "--port", str(port),
               "--duration", str(N_HOSTILE_SECONDS),
               "--flows", str(HOSTILE_FLOWS),
               "--qps", str(HOSTILE_QPS),
               "--domain", "bench.com",
               "--names", ",".join(names)],
            cwd=ROOT, env=_bench_env(), stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        time.sleep(0.5)   # let the flood trip the limiter first
        under = legit_probe("127.0.0.1", port,
                            duration=max(2.0, N_HOSTILE_SECONDS - 1.5),
                            names=names, qps=HOSTILE_LEGIT_QPS)
        out, _ = flood.communicate(timeout=N_HOSTILE_SECONDS + 60)
        if flood.returncode != 0:
            raise RuntimeError("hostile axis: harness exited "
                               f"{flood.returncode}")
        report = json.loads(out)
        rrl = shed = None
        try:
            rrl = _scrape_rrl(mport)
            shed = _scrape_shed(mport)
        except Exception as e:  # noqa: BLE001 — supplementary figure
            print(f"bench: hostile rrl scrape failed: {e!r}",
                  file=sys.stderr)
        ratio = (under["qps"] / control["qps"]) if control["qps"] else 0.0
        return {
            "control_qps": control["qps"],
            "under_flood_qps": under["qps"],
            "goodput_ratio": round(ratio, 3),
            "legit_offered_qps": HOSTILE_LEGIT_QPS,
            "legit_timeouts": under["timeouts"],
            "hostile_qps": report["hostile_qps"],
            "flows": report["flows"],
            "mix": report["mix"],
            "duration_s": report["duration_s"],
            # client-side shed/refuse attribution per category
            "categories": report["categories"],
            # server-side attribution: the same flood as the scrape
            # tells it (binder_rrl_* + binder_shed_total by reason)
            "rrl": rrl,
            "shed": shed,
            "default_mix": DEFAULT_MIX,
        }
    finally:
        if flood is not None:
            _reap(flood)
        _reap(proc)


VERIFY_ZONES = os.environ.get("BENCH_VERIFY_ZONES", "10000,1000000")
N_VERIFY_MUTATIONS = int(os.environ.get("BENCH_VERIFY_MUTATIONS", "400"))

_VERIFY_LINE = re.compile(
    r'^binder_verify_(checks|violations|skipped)_total'
    r'\{[^}]*invariant="([^"]+)"[^}]*\} ([0-9.eE+-]+)$', re.M)


def _scrape_verify(metrics_port: int) -> Dict[str, Dict[str, float]]:
    """The `binder_verify_*` counters off a live scrape — proof the ON
    side of the verify A/B was actually checking (checks advancing)
    and that the zone it checked was clean (violations zero)."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/metrics", timeout=5) as r:
        text = r.read().decode()
    out: Dict[str, Dict[str, float]] = {
        "checks": {}, "violations": {}, "skipped": {}}
    for kind, inv, value in _VERIFY_LINE.findall(text):
        v = float(value)
        if v:
            out[kind][inv] = out[kind].get(inv, 0.0) + v
    return out


def _bench_verify(tmpdir: str) -> Dict[str, object]:
    """Verify axis (ISSUE 16), two halves.  (a) Mutation→glass
    propagation p50/p99 per stage at each BENCH_VERIFY_ZONES size —
    one tools/verify_probe.py subprocess per size (RSS isolation, the
    zone_scale discipline) records the tracer's per-stage figures
    (end-to-end from the store event, what binder_propagation_seconds
    sees), the checker's inline worst-case mutation cost, and one full
    audit pass with its worst slice; flat glass-latency from the
    smallest size to 1M is the O(delta) acceptance.  (b) The
    headline-qps cost of running the verify plane at all: two
    identical servers, one with the subsystem ON (the production
    default — incremental checker + 4 Hz audit + tracer) and one with
    `verify.enabled: false`, driven in interleaved A-B-A-B passes
    inside one window so box drift cancels out of the estimate (the
    balancer-overhead discipline) — acceptance: overhead <= 1%."""
    sizes = [int(s) for s in VERIFY_ZONES.split(",") if s.strip()]
    per_size: Dict[str, dict] = {}
    for n in sizes:
        o = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "tools", "verify_probe.py"),
             str(n), str(N_VERIFY_MUTATIONS)],
            capture_output=True, text=True, check=True,
            timeout=600 + n // 1000)
        per_size[str(n)] = json.loads(o.stdout)

    fixture = os.path.join(tmpdir, "verify_fixture.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    base = {"dnsDomain": "bench.com", "datacenterName": "dc0",
            "host": "127.0.0.1", "queryLog": False,
            "store": {"backend": "fake", "fixture": fixture}}
    on_cfg = os.path.join(tmpdir, "verify_on.json")
    with open(on_cfg, "w") as f:
        json.dump({**base, "verify": {}}, f)
    off_cfg = os.path.join(tmpdir, "verify_off.json")
    with open(off_cfg, "w") as f:
        json.dump({**base, "verify": {"enabled": False}}, f)
    rounds = max(3, N_PASSES)
    procs: List[subprocess.Popen] = []
    try:
        on = _launch_server(on_cfg)
        procs.append(on)
        on_port, on_mport = wait_for_ports(on)
        off = _launch_server(off_cfg)
        procs.append(off)
        off_port = wait_for_port(off)

        _drive_native(on_port, tmpdir)    # warm both sides
        _drive_native(off_port, tmpdir)
        on_passes: List[Dict[str, float]] = []
        off_passes: List[Dict[str, float]] = []
        for _ in range(rounds):
            on_passes.append(_drive_native(on_port, tmpdir))
            off_passes.append(_drive_native(off_port, tmpdir))

        def med(passes):
            passes = sorted(passes, key=lambda r: r["qps"])
            r = dict(passes[len(passes) // 2])
            r["qps_spread"] = round(
                passes[-1]["qps"] - passes[0]["qps"], 1)
            return r

        on_res, off_res = med(on_passes), med(off_passes)
        scrape = None
        try:
            scrape = _scrape_verify(on_mport)
        except OSError as e:
            print(f"bench: verify scrape failed: {e!r}",
                  file=sys.stderr)
    finally:
        for p in procs:
            _reap(p)

    largest = per_size[str(sizes[-1])]
    smallest = per_size[str(sizes[0])]

    def glass(entry, pct):
        s = entry.get("propagation", {}).get("compiled-install")
        return s.get(pct) if s else None

    g_small, g_large = glass(smallest, "p50_us"), glass(largest, "p50_us")
    live_violations = sum(
        (scrape or {}).get("violations", {}).values())
    return {
        "sizes": sizes,
        "per_size": per_size,
        # the acceptance headlines, precomputed so the JSON answers
        # them without arithmetic
        "on_qps": round(on_res["qps"], 1),
        "on_qps_spread": on_res["qps_spread"],
        "off_qps": round(off_res["qps"], 1),
        "off_qps_spread": off_res["qps_spread"],
        "overhead_pct": round(
            (1.0 - on_res["qps"] / off_res["qps"]) * 100.0, 1),
        "passes": rounds,
        "glass_p50_us_largest": g_large,
        "glass_p99_us_largest": glass(largest, "p99_us"),
        "glass_flatness": round(g_large / g_small, 2)
        if g_large and g_small else None,
        "audit_worst_slice_ms_largest":
            largest["audit_worst_slice_ms"],
        "violations": sum(e["violations"] for e in per_size.values())
        + int(live_violations),
        "verify_scrape": scrape,
    }


N_POPULATION_SECONDS = float(
    os.environ.get("BENCH_POPULATION_SECONDS", "16"))
POPULATION_IDENTITIES = int(
    os.environ.get("BENCH_POPULATION_IDENTITIES", "100000"))
POPULATION_QPS_PEAK = int(
    os.environ.get("BENCH_POPULATION_QPS", "1500"))
#: aggregate limit low enough that a NAT'd farm overdraws it — the
#: false-positive mechanism the adaptive arm must fix (same posture
#: tools/population_smoke.py pins)
POPULATION_RRL = {"responsesPerSecond": 60, "burst": 120,
                  "slipRatio": 2, "adaptEvidence": 3,
                  "allowlist": ["127.10.0.0/16"]}


def _bench_population(tmpdir: str) -> Dict[str, object]:
    """Population axis (ISSUE 19): million-client realism figures.

    Three headline numbers:

    - ``goodput_ratio`` — NAT'd-farm goodput (answered + TCP-retry
      completions over sent) under the Zipf/NAT population model
      (tools/population.py) with adaptive RRL;
    - ``fp_rate_adaptive`` vs ``fp_rate_static`` — the measured RRL
      false-positive rate with adaptive bucket sizing on vs off,
      interleaved A-B-A-B in one window so box drift cancels out of
      the comparison (the balancer-overhead pattern);
    - ``roll.query_loss`` — closed-loop probe queries fully lost
      across a SIGHUP-triggered 2-shard rolling drain-and-replace
      (acceptance: zero; a bounded retry is tolerated, a loss is not).
    """
    from tools.population import run_population

    fixture = os.path.join(tmpdir, "population_fixture.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    names = ["web.bench.com", "svc.bench.com"]

    def boot(tag: str, adaptive: bool, shards: int = 0,
             allowlist=None):
        config = os.path.join(tmpdir, f"population_{tag}.json")
        rrl = dict(POPULATION_RRL)
        rrl["adaptive"] = adaptive
        if allowlist is not None:
            rrl["allowlist"] = list(allowlist)
        with open(config, "w") as f:
            json.dump({
                "dnsDomain": "bench.com", "datacenterName": "dc0",
                "host": "127.0.0.1",
                "store": {"backend": "fake", "fixture": fixture},
                "queryLog": False, "rrl": rrl,
                **({"shards": shards} if shards else {}),
            }, f)
        return _launch_server(config)

    # -- interleaved A/B: adaptive vs static buckets --
    seg = max(2.0, N_POPULATION_SECONDS / 4)
    arms: Dict[str, list] = {"adaptive": [], "static": []}
    scrapes: Dict[str, list] = {"adaptive": [], "static": []}
    for idx, arm in enumerate(("adaptive", "static",
                               "adaptive", "static")):
        proc = boot(f"{arm}{idx}", arm == "adaptive")
        try:
            port, mport = wait_for_ports(proc)
            # same seed per arm pass: both postures face the SAME
            # offered population, so the FP delta is the mechanism
            rep = run_population(
                "127.0.0.1", port, duration=seg, names=names,
                domain="bench.com", identities=POPULATION_IDENTITIES,
                qps_floor=300, qps_peak=POPULATION_QPS_PEAK,
                seed=7 + idx // 2)
            arms[arm].append(rep)
            try:
                scrapes[arm].append(_scrape_rrl(mport))
            except Exception as e:  # noqa: BLE001 — supplementary
                print(f"bench: population rrl scrape failed: {e!r}",
                      file=sys.stderr)
        finally:
            _reap(proc)

    def mean(arm: str, key: str) -> float:
        vals = [r[key] for r in arms[arm]]
        return sum(vals) / len(vals) if vals else 0.0

    def scraped(arm: str, key: str) -> float:
        return sum(s.get(key, 0.0) for s in scrapes[arm])

    if not arms["adaptive"] or not arms["static"]:
        raise RuntimeError("population axis: an A/B arm never ran")
    # the adaptive arm must actually have adapted — otherwise the A/B
    # compares identical mechanisms and the delta is pure noise
    if scrapes["adaptive"] and not scraped("adaptive",
                                           "adaptations_total"):
        raise RuntimeError("population axis: adaptive arm recorded "
                           "zero adaptations")

    # -- rolling-upgrade probe loss (2 shards, SIGHUP entry) --
    roll: Dict[str, object] = {}
    proc = boot("roll", True, shards=2, allowlist=["127.0.0.0/24"])
    try:
        port, mport = wait_for_ports(proc)
        probe_wire = make_query(names[0], Type.A, qid=77).encode()
        sent = lost = retried = 0
        signalled = False
        rolls_total = 0
        deadline = time.time() + max(10.0, N_POPULATION_SECONDS)
        while time.time() < deadline:
            tries = 0
            for attempt in range(3):
                s = _socket_mod.socket(_socket_mod.AF_INET,
                                       _socket_mod.SOCK_DGRAM)
                s.settimeout(1.0)
                s.connect(("127.0.0.1", port))
                try:
                    s.send(probe_wire)
                    s.recv(4096)
                    tries = attempt + 1
                    break
                except _socket_mod.timeout:
                    continue
                finally:
                    s.close()
            sent += 1
            if tries == 0:
                lost += 1
            elif tries > 1:
                retried += 1
            if sent == 20 and not signalled:
                proc.send_signal(signal.SIGHUP)
                signalled = True
            if sent % 10 == 0:
                snap = _shard_status(mport)["shards"]
                rolls_total = snap["rolls_total"]
                roll["roll_aborts"] = snap["roll_aborts"]
                if rolls_total >= 2:
                    break
            time.sleep(0.01)
        if rolls_total < 2:
            raise RuntimeError("population axis: rolling upgrade did "
                               f"not complete ({rolls_total}/2 shards)")
        roll.update({"probes": sent, "query_loss": lost,
                     "retried": retried, "rolls_total": rolls_total})
    finally:
        _reap(proc)

    shape = arms["adaptive"][0]["population"]
    fp_adaptive = mean("adaptive", "rrl_false_positive_rate")
    fp_static = mean("static", "rrl_false_positive_rate")
    return {
        "identities": shape["identities"],
        "prefixes": shape["prefixes"],
        "zipf_s": shape["zipf_s"],
        "nat_fan_in": shape["nat_fan_in"],
        "offered_qps_peak": POPULATION_QPS_PEAK,
        "segment_s": round(seg, 1),
        # headline 1: farm goodput under adaptive RRL
        "goodput_ratio": round(mean("adaptive", "farm_goodput_ratio"),
                               4),
        "goodput_ratio_static": round(
            mean("static", "farm_goodput_ratio"), 4),
        # headline 2: measured FP rate, adaptive vs static (A/B)
        "fp_rate_adaptive": round(fp_adaptive, 4),
        "fp_rate_static": round(fp_static, 4),
        "fp_rate_delta": round(fp_static - fp_adaptive, 4),
        # headline 3: rolling-upgrade probe loss (acceptance: zero)
        "roll": roll,
        "rrl": {
            "adaptations": scraped("adaptive", "adaptations_total"),
            "adapted_buckets": scraped("adaptive", "adapted_buckets"),
            "allowlisted": scraped("adaptive", "allowlisted_total"),
            "false_positives": scraped("adaptive",
                                       "false_positives_total"),
        },
        "arms": {
            arm: [{"goodput": r["farm_goodput_ratio"],
                   "fp_rate": r["rrl_false_positive_rate"],
                   "outcomes": r["identity_outcomes"]}
                  for r in arms[arm]]
            for arm in ("adaptive", "static")
        },
    }


def _try_axis(name: str, fn, retries: int = 1):
    """Run one bench axis, retrying once on failure: every axis is
    exception-guarded so a transient (a busy box stretching a startup
    deadline) must cost a retry, not the round's only recorded figures.
    Failures are loud on stderr; stdout stays the single JSON line."""
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — any axis failure
            print(f"bench: {name} axis failed "
                  f"(attempt {attempt + 1}/{retries + 1}): {e!r}",
                  file=sys.stderr)
    return None


def run_bench() -> Dict[str, object]:
    env = _env_fingerprint()   # loadavg sampled before any load
    topo = miss = churn = recur = fronted1 = logged = tcp = None
    realistic = degraded = shard = zone_scale = cross_dc = None
    hostile = verify_ax = population = None
    with tempfile.TemporaryDirectory() as tmpdir:
        proc = start_server(tmpdir)
        try:
            port = wait_for_port(proc)
            if os.access(DNSBLAST, os.X_OK):
                res = _median_passes(
                    lambda: _drive_native(port, tmpdir), N_PASSES)
            else:
                res = asyncio.run(_drive(port))
        finally:
            proc.terminate()
            proc.wait(timeout=10)
        if os.access(DNSBLAST, os.X_OK):
            logged = _try_axis("logged", lambda: _bench_logged(tmpdir))
            tcp = _try_axis("tcp", lambda: _bench_tcp(tmpdir))
            miss = _try_axis("miss", lambda: _bench_miss(tmpdir))
            churn = _try_axis("churn", lambda: _bench_churn(tmpdir))
            recur = _try_axis("recursion",
                              lambda: _bench_recursion(tmpdir))
            realistic = _try_axis("realistic",
                                  lambda: _bench_realistic(tmpdir))
            degraded = _try_axis("degraded",
                                 lambda: _bench_degraded(tmpdir))
            shard = _try_axis("shard", lambda: _bench_shard(tmpdir))
            zone_scale = _try_axis("zone_scale",
                                   lambda: _bench_zone_scale(tmpdir))
            cross_dc = _try_axis("cross_dc",
                                 lambda: _bench_cross_dc(tmpdir))
            hostile = _try_axis("hostile",
                                lambda: _bench_hostile(tmpdir))
            verify_ax = _try_axis("verify",
                                  lambda: _bench_verify(tmpdir))
        # pure-Python harness: no dnsblast dependency — the population
        # model's realism is the point, not raw packet rate
        population = _try_axis("population",
                               lambda: _bench_population(tmpdir))
        if os.access(DNSBLAST, os.X_OK) and os.access(MBALANCER, os.X_OK):
            topo = _try_axis("topology", lambda: _bench_topology(tmpdir))
            # balancer-overhead isolation (VERDICT r3 item 2): the
            # SAME workload against ONE backend, direct and
            # balancer-fronted, interleaved A-B-A-B in one time window
            # so box drift cancels out of the estimate (see
            # _bench_balancer_overhead)
            fronted1 = _try_axis("balancer-overhead",
                                 lambda: _bench_balancer_overhead(tmpdir))

    baseline = miss_baseline = None
    legacy_baseline = False   # round-1 file predating the miss axis
    if os.path.exists(BASELINE_FILE):
        try:
            with open(BASELINE_FILE) as f:
                b = json.load(f)
                baseline = b.get("qps")
                miss_baseline = b.get("miss_qps")
                legacy_baseline = "miss_qps" not in b
        except (OSError, ValueError):
            baseline = None
    if not baseline:
        # first measured values become the local baseline (the reference
        # publishes no numbers — BASELINE.md); the miss axis gets its own
        # baseline so the cold-path ratio never silently compares
        # against a hot-path figure
        with open(BASELINE_FILE, "w") as f:
            json.dump({"qps": res["qps"],
                       "miss_qps": miss["qps"] if miss else None,
                       "note": "first local measurement; reference "
                               "publishes no numbers (BASELINE.md)"}, f)
        baseline = res["qps"]
        miss_baseline = miss["qps"] if miss else None
    elif miss is not None and not miss_baseline and not legacy_baseline:
        # new-format baseline whose miss axis failed on the first run:
        # backfill now so the cold ratio never compares against the
        # hot-path figure
        try:
            with open(BASELINE_FILE) as f:
                b = json.load(f)
            b["miss_qps"] = miss["qps"]
            with open(BASELINE_FILE, "w") as f:
                json.dump(b, f)
        except (OSError, ValueError):
            pass
        miss_baseline = miss["qps"]
    if not miss_baseline:
        # legacy round-1 baseline file: its single qps figure WAS a
        # pure-Python resolve-path measurement, i.e. the honest cold
        # comparator (docs/bench.md)
        miss_baseline = baseline

    out = {"metric": "dns_queries_per_sec"}
    if logged is not None:
        # REFERENCE-PARITY HEADLINE (VERDICT r5 item 1, reporting
        # half): the reference logs every query unconditionally, so the
        # logged posture IS the comparable number — it leads the JSON,
        # with the log-off figure below it as the ceiling.  Served by
        # the native path through the log ring; the ratio shows what
        # the posture costs (was ~9x before r5's ring).
        out["logged_qps"] = round(logged["qps"], 1)
        out["logged_qps_spread"] = logged.get("qps_spread")
        out["logged_p50_us"] = round(logged["p50_us"], 1)
        out["logged_p99_us"] = round(logged["p99_us"], 1)
        out["logged_log_lines"] = logged["log_lines"]
    out.update({
        "value": round(res["qps"], 1),
        "unit": "qps",
        "vs_baseline": round(res["qps"] / baseline, 3),
        "qps_spread": res.get("qps_spread"),
        "p50_us": round(res["p50_us"], 1),
        "p99_us": round(res["p99_us"], 1),
        "p99_spread_us": res.get("p99_spread_us"),
        "errors": res["errors"],
        "retries": res.get("retries", 0),
        "queries": N_QUERIES,
        "concurrency": CONCURRENCY,
    })
    if logged is not None:
        out["logged_vs_headline"] = round(logged["qps"] / res["qps"], 3)
    if tcp is not None:
        # TCP serving (persistent pipelined conns / conn-per-query /
        # the tc=1 UDP->TCP retry flow); attribution: the TCP lane is
        # asyncio streams + the socket-free native serve entry, not the
        # batched C drain, so a gap vs the UDP headline is expected
        out["tcp_qps"] = round(tcp["qps"], 1)
        out["tcp_qps_spread"] = tcp.get("qps_spread")
        out["tcp_p50_us"] = round(tcp["p50_us"], 1)
        out["tcp_p99_us"] = round(tcp["p99_us"], 1)
        # interleaved A/B: the drift-cancelled TCP-vs-UDP ratio and the
        # in-window UDP control it was measured against
        out["tcp_vs_udp"] = tcp.get("vs_udp")
        out["tcp_udp_ref_qps"] = tcp.get("udp_ref_qps")
        out["tcp1_qps"] = tcp.get("tcp1_qps")
        out["tcp1_qps_spread"] = tcp.get("tcp1_qps_spread")
        out["tcp1_p99_us"] = tcp.get("tcp1_p99_us")
        out["tc_retry_flows_per_s"] = tcp.get("tc_retry_flows_per_s")
        out["tc_retry_p50_us"] = tcp.get("tc_retry_p50_us")
    if miss is not None:
        # cache-cold axis: every name queried exactly once (zone
        # precompile = the production cold path; engine_* = the Python
        # resolve path with precompile off, its own regression gate)
        out["miss_qps"] = round(miss["qps"], 1)
        out["miss_qps_spread"] = miss.get("qps_spread")
        out["miss_p50_us"] = round(miss["p50_us"], 1)
        out["miss_p99_us"] = round(miss["p99_us"], 1)
        out["miss_vs_baseline"] = round(miss["qps"] / miss_baseline, 3)
        out["miss_queries"] = N_MISS
        if "engine_qps" in miss:
            out["miss_engine_qps"] = miss["engine_qps"]
            out["miss_engine_qps_spread"] = miss.get("engine_qps_spread")
            out["miss_engine_p99_us"] = miss.get("engine_p99_us")
            out["miss_engine_vs_baseline"] = round(
                miss["engine_qps"] / miss_baseline, 3)
        if "lazy_qps" in miss:
            # the bare resolve-per-query path with BOTH precompile
            # layers off — the engine's own regression gate, and the
            # comparator that makes the engine figure's movement
            # attributable to mutation-time precompilation
            out["miss_lazy_qps"] = miss["lazy_qps"]
            out["miss_lazy_qps_spread"] = miss.get("lazy_qps_spread")
            out["miss_lazy_p99_us"] = miss.get("lazy_p99_us")
    if churn is not None:
        # hot mix under sustained store mutation via the real ZK wire
        # protocol: watch delivery + per-name invalidation under load
        out["churn_qps"] = round(churn["qps"], 1)
        out["churn_qps_spread"] = churn.get("qps_spread")
        out["churn_p50_us"] = round(churn["p50_us"], 1)
        out["churn_p99_us"] = round(churn["p99_us"], 1)
        out["churn_mutations_per_s"] = round(churn["mutations_per_s"], 1)
        if "mixed_qps" in churn:
            # the precompile-aware churn measurement: the query mix
            # includes the churning names, so cached answers are
            # invalidated-then-requeried several times a second — the
            # path mutation-time precompilation exists for
            out["churn_mixed_qps"] = round(churn["mixed_qps"], 1)
            out["churn_mixed_p50_us"] = round(churn["mixed_p50_us"], 1)
            out["churn_mixed_p99_us"] = round(churn["mixed_p99_us"], 1)
        if churn.get("precompile"):
            # the mutation-time pipeline's economics over the measured
            # windows: compiled/shed/serves name whether churn latency
            # moved because of precompilation or despite it
            out["churn_precompile"] = churn["precompile"]
        if "topo_qps" in churn:
            # the same churn through the balancer (opcode-1 per-name
            # invalidation keeps its cache hot for unmutated names)
            out["churn_topology_qps"] = round(churn["topo_qps"], 1)
            out["churn_topology_p99_us"] = round(churn["topo_p99_us"], 1)
    if recur is not None:
        # cross-DC forwarding (BASELINE.json proxy config 'recursive
        # resolution'): per-query upstream round trip, never cached
        out["recursion_qps"] = round(recur["qps"], 1)
        out["recursion_qps_spread"] = recur.get("qps_spread")
        out["recursion_p50_us"] = round(recur["p50_us"], 1)
        out["recursion_p99_us"] = round(recur["p99_us"], 1)
        if recur.get("attribution"):
            # per-stage split of the forwarder's time (scraped
            # binder_query_stage_seconds): upstream-rtt vs loop-wait
            # vs splice etc., with the owning stage named — the 7.3ms
            # p50 question is answered in the JSON, not guessed at
            out["recursion_attribution"] = recur["attribution"]
    if realistic is not None:
        # the combined realistic posture (round-5 VERDICT ask): logging
        # + TCP + churn + recursion at once — the no-excuses number
        out["realistic_qps"] = round(realistic["qps"], 1)
        out["realistic_p50_us"] = round(realistic["p50_us"], 1)
        out["realistic_p99_us"] = round(realistic["p99_us"], 1)
        out["realistic_udp_qps"] = round(realistic["udp_qps"], 1)
        out["realistic_tcp_qps"] = round(realistic["tcp_qps"], 1)
        out["realistic_errors"] = realistic["errors"]
        out["realistic_mutations_per_s"] = round(
            realistic["mutations_per_s"], 1)
        out["realistic_recursion_share"] = round(
            realistic["recursion_share"], 3)
        out["realistic_log_lines"] = realistic.get("log_lines")
        if realistic.get("precompile"):
            out["realistic_precompile"] = realistic["precompile"]
    if degraded is not None:
        # degradation axis (ISSUE 4): the hot mix served STALE
        # (session lost, within cap — TTL-clamped generic path, raw
        # lane/native standing down) and WITHHELD (past cap — every
        # query an immediate well-formed SERVFAIL); both scripted via
        # the server's own chaos config block and scrape-asserted to
        # be measuring the intended state (docs/degradation.md)
        out["degraded_qps"] = round(degraded["qps"], 1)
        out["degraded_qps_spread"] = degraded.get("qps_spread")
        out["degraded_p50_us"] = round(degraded["p50_us"], 1)
        out["degraded_p99_us"] = round(degraded["p99_us"], 1)
        out["degraded_withheld_qps"] = round(degraded["withheld_qps"], 1)
        out["degraded_withheld_p99_us"] = round(
            degraded["withheld_p99_us"], 1)
    if shard is not None:
        # shard axis (ISSUE 6): N worker processes behind one kernel-
        # balanced SO_REUSEPORT port, one mirror owner.  qps/efficiency
        # keyed by N; `inproc` is the no-supervisor control measured
        # with the SAME multi-process client topology, so
        # shard1_overhead_pct is the honest cost of the mechanism at
        # N=1 (ideal = min(N, cores); on a 1-core box N>1 efficiency
        # is expected < 1 and the mechanism numbers are the point)
        out["shard_qps"] = shard["qps"]
        out["shard_qps_spread"] = shard["qps_spread"]
        out["shard_p50_us"] = shard["p50_us"]
        out["shard_p99_us"] = shard["p99_us"]
        out["shard_efficiency"] = shard.get("efficiency")
        out["shard_balance"] = shard["balance"]
        out["shard_inproc_ref_qps"] = shard.get("inproc_qps")
        out["shard1_overhead_pct"] = shard.get("shard1_overhead_pct")
        out["shard_clients"] = shard["clients"]
        # the env block carries the shard PIDs/cores so the "N
        # distinct processes on M cores" claim is checkable in the JSON
        env["shard_pids"] = shard["pids"]
        env["shard_cores"] = shard["cores"]
    if zone_scale is not None:
        # zone_scale axis (ISSUE 7): mirror build/RSS/mutation-latency
        # probes per size plus steady-state headline-mix qps at
        # 10k/100k/1M names with the 100-name figure as control.  The
        # summary keys answer the acceptance criteria directly:
        # RSS/name vs the replaced dict-per-node representation,
        # mutation latency flat from small to 1M (O(delta)), qps at the
        # largest size within noise of the control, and the chunked
        # session rebuild's worst observed loop stall.
        out["zone_scale"] = zone_scale
    if cross_dc is not None:
        # cross_dc axis (ISSUE 11): foreign (registry-routed, forwarded
        # to the owning DC) vs local p50/p99 through one federated
        # binder, plus how long foreign names stay unanswered when the
        # whole owning DC dies before the stale-serve path takes over
        out["cross_dc"] = cross_dc
    if hostile is not None:
        # hostile axis (ISSUE 12): paced legit goodput under the
        # adversarial multi-flow flood, with both client-side
        # (per-category answered/refused/slipped/dropped) and
        # server-side (binder_rrl_* / binder_shed_total) attribution —
        # goodput_ratio is the acceptance figure (>= 0.8)
        out["hostile"] = hostile
        # the env block records the harness shape (flow count + mix)
        # so cross-round hostile figures are comparable (satellite f)
        env["hostile_flows"] = hostile["flows"]
        env["hostile_mix"] = hostile["mix"]
        env["hostile_offered_qps"] = HOSTILE_QPS
    if population is not None:
        # population axis (ISSUE 19): NAT'd-farm goodput + measured
        # RRL false-positive rate (adaptive-vs-static interleaved A/B)
        # + rolling-upgrade probe loss (acceptance: zero)
        out["population"] = population
        # env block records the population shape so cross-round
        # figures are comparable (identities, source prefixes, Zipf
        # skew, NAT fan-in — the knobs that set RRL pressure)
        env["population_identities"] = population["identities"]
        env["population_prefixes"] = population["prefixes"]
        env["population_zipf_s"] = population["zipf_s"]
        env["population_nat_fan_in"] = population["nat_fan_in"]
        env["population_offered_qps"] = POPULATION_QPS_PEAK
    if verify_ax is not None:
        # verify axis (ISSUE 16): mutation→glass per-stage p50/p99 at
        # each zone size (flat = O(delta)), the checker's inline
        # worst-case mutation cost, one full audit pass per size, and
        # the interleaved A/B headline cost of the verify plane —
        # overhead_pct is the acceptance figure (<= 1%), violations
        # must be 0 on the uncorrupted bench zones
        out["verify"] = verify_ax
    if topo is not None:
        # supplementary: deployment shape (balancer + 2 backends), warm,
        # with the balancer's own per-stage attribution riding along
        out["topology_qps"] = round(topo["qps"], 1)
        out["topology_qps_spread"] = topo.get("qps_spread")
        out["topology_p50_us"] = round(topo["p50_us"], 1)
        out["topology_cache_hit_pct"] = topo.get("cache_hit_pct")
        out["topology_fwd_rtt_p99_us"] = topo.get("fwd_rtt_p99_us")
        out["topology_backend_wq_peak"] = topo.get("backend_wq_peak")
    if topo is not None and topo.get("attribution"):
        out["topology_attribution"] = topo["attribution"]
    if fronted1 is not None:
        # balancer-overhead isolation: identical workload, one backend,
        # direct vs fronted measured in interleaved passes within one
        # window — the overhead is a same-environment ratio, so
        # consecutive full runs agree on it (the 7.7%→15.6%→−31.6%
        # history was the comparator drifting, not the balancer)
        out["balancer_direct1_qps"] = fronted1["direct_qps"]
        out["balancer_direct1_qps_spread"] = fronted1["direct_qps_spread"]
        out["balancer_fronted1_qps"] = fronted1["fronted_qps"]
        out["balancer_fronted1_qps_spread"] = fronted1["fronted_qps_spread"]
        out["balancer_overhead_pct"] = fronted1["overhead_pct"]
        # third interleaved arm (ISSUE 18): the classic relay (`-D`)
        # measured in the same window, so the direct-return win is a
        # same-environment ratio against both baselines
        out["balancer_relay1_qps"] = fronted1.get("relay_qps")
        out["balancer_relay1_qps_spread"] = fronted1.get(
            "relay_qps_spread")
        out["balancer_relay_overhead_pct"] = fronted1.get(
            "relay_overhead_pct")
        for arm in ("fronted", "relay"):
            blk = fronted1.get(arm)
            if blk:
                out[f"balancer_{arm}_syscalls_per_query"] = blk.get(
                    "syscalls_per_query")
                out[f"balancer_{arm}_udp_batch_cells"] = blk.get(
                    "udp_batch_cells")
        if fronted1.get("fronted"):
            out["balancer_direct_forwards"] = fronted1["fronted"].get(
                "direct_forwards")
            out["balancer_fd_passes"] = fronted1["fronted"].get(
                "fd_passes")
        if fronted1.get("attribution"):
            # which stage of the balancer's own packet path owns the
            # overhead (stage_cycles, docs/balancer-protocol.md) —
            # reply-relay share should be collapsed on the
            # direct-return arm vs the relay arm's block
            out["balancer_attribution"] = fronted1["attribution"]
        if fronted1.get("relay", {}).get("attribution"):
            out["balancer_relay_attribution"] = \
                fronted1["relay"]["attribution"]
    out["env"] = env
    return out
