"""Full-stack DNS benchmark (invoked by bench.py).

Measures the BASELINE.md proxy metric — DNS queries/sec and resolve-latency
percentiles — against a REAL binder server process (`python -m
binder_tpu.main`) over loopback UDP, dnsperf-style: the load generator
keeps a window of queries in flight and only parses the response id +
rcode, so the measurement is server capacity, not client parsing.

Query mix mirrors BASELINE.json's proxy configs: single-host A lookups,
round-robin service A lookups, SRV lookups, and PTR lookups.  The server
runs with queryLog disabled (per-query JSON logging is an ops knob;
latency histograms still observe every query — the reference's bunyan
per-query logging would equally dominate any single-machine benchmark).
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import select
import subprocess
import sys
import tempfile
import time
from typing import Dict, List

from binder_tpu.dns import Type, make_query

ROOT = os.path.dirname(os.path.abspath(__file__))
N_QUERIES = int(os.environ.get("BENCH_QUERIES", "50000"))
CONCURRENCY = int(os.environ.get("BENCH_CONCURRENCY", "64"))
BASELINE_FILE = os.path.join(ROOT, "BENCH_BASELINE.json")

# query mix mirroring BASELINE.json's proxy configs; shared by the native
# and Python load drivers so both measure the same workload
BENCH_MIX = [
    ("web.bench.com", Type.A),
    ("svc.bench.com", Type.A),
    ("_http._tcp.svc.bench.com", Type.SRV),
    ("1.0.1.10.in-addr.arpa", Type.PTR),
]

FIXTURE = {
    "/com/bench/web": {"type": "host", "host": {"address": "10.1.0.1"}},
    "/com/bench/svc": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 8080},
    },
    **{f"/com/bench/svc/lb{i}":
       {"type": "load_balancer",
        "load_balancer": {"address": f"10.1.1.{i + 1}"}}
       for i in range(8)},
}


class BenchClient(asyncio.DatagramProtocol):
    """Windowed UDP load generator with timeout-retransmit (loopback UDP
    still drops under bursts; a stalled window would hang the run)."""

    RETRY_AFTER = 1.0

    def __init__(self, queries: List[bytes], done: asyncio.Future) -> None:
        self.queries = queries
        self.done = done
        self.next_idx = 0
        self.received = 0
        self.latencies: List[float] = []
        self.outstanding: Dict[int, float] = {}   # qid -> last-sent-at
        self.retried: set = set()   # qids whose latency is tainted
        self.errors = 0
        self.retries = 0

    def connection_made(self, transport) -> None:
        self.transport = transport
        for _ in range(min(CONCURRENCY, len(self.queries))):
            self._send_next()

    def _send_next(self) -> None:
        i = self.next_idx
        if i >= len(self.queries):
            return
        self.next_idx += 1
        self.outstanding[i] = time.perf_counter()
        self.transport.sendto(self.queries[i])

    def retransmit_stale(self) -> None:
        now = time.perf_counter()
        for qid, t0 in list(self.outstanding.items()):
            if now - t0 > self.RETRY_AFTER:
                self.retries += 1
                self.retried.add(qid)   # latency not counted
                self.outstanding[qid] = now   # keep retrying until answered
                self.transport.sendto(self.queries[qid])

    def datagram_received(self, data, addr) -> None:
        now = time.perf_counter()
        qid = (data[0] << 8) | data[1]
        t0 = self.outstanding.pop(qid, None)
        if t0 is None:
            return   # duplicate response to a retransmit
        if qid not in self.retried:
            self.latencies.append(now - t0)
        if data[3] & 0x0F:   # rcode nibble
            self.errors += 1
        self.received += 1
        if self.received >= len(self.queries):
            if not self.done.done():
                self.done.set_result(None)
        else:
            self._send_next()


def start_server(tmpdir: str) -> subprocess.Popen:
    fixture = os.path.join(tmpdir, "fixture.json")
    config = os.path.join(tmpdir, "config.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    with open(config, "w") as f:
        json.dump({
            "dnsDomain": "bench.com", "datacenterName": "dc0",
            "host": "127.0.0.1",
            "store": {"backend": "fake", "fixture": fixture},
            "queryLog": False,
        }, f)
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-u", "-m", "binder_tpu.main", "-f", config,
         "-p", "0"],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)


def _wait_for_line(proc: subprocess.Popen, pattern: bytes,
                   what: str) -> int:
    """Deadline-bounded read of proc stdout until `pattern` matches;
    returns the captured int.  A child that wedges mid-startup (or
    writes a partial line) must not hang the bench."""
    deadline = time.time() + 30
    buf = b""
    while time.time() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(0.0, deadline - time.time()))
        if not ready:
            break
        chunk = os.read(proc.stdout.fileno(), 4096)
        if not chunk:
            raise RuntimeError("%s exited during startup" % what)
        buf += chunk
        m = re.search(pattern, buf)
        if m:
            return int(m.group(1))
    raise RuntimeError("%s did not report its port within 30s" % what)


def wait_for_port(proc: subprocess.Popen) -> int:
    # patterns must anchor past the number, or a mid-number pipe-buffer
    # split ("...:444" / "28\"...") yields a truncated port; the bunyan
    # msg is JSON, so the port is terminated by the closing quote
    return _wait_for_line(
        proc, rb"UDP DNS service started on [\d.]+:(\d+)\"", "bench server")


async def _drive(port: int) -> Dict[str, float]:
    # qids must be unique across the in-flight window; id space is 64k
    assert N_QUERIES <= 65536
    queries = [make_query(*BENCH_MIX[i % len(BENCH_MIX)],
                          qid=i % 65536).encode()
               for i in range(N_QUERIES)]

    loop = asyncio.get_running_loop()
    done = loop.create_future()
    t0 = time.perf_counter()
    transport, proto = await loop.create_datagram_endpoint(
        lambda: BenchClient(queries, done),
        remote_addr=("127.0.0.1", port))

    async def watchdog():
        while not done.done():
            await asyncio.sleep(0.25)
            proto.retransmit_stale()

    wd = asyncio.ensure_future(watchdog())
    await asyncio.wait_for(done, timeout=300)
    elapsed = time.perf_counter() - t0
    wd.cancel()
    transport.close()

    lats = sorted(proto.latencies)
    return {
        "qps": N_QUERIES / elapsed,
        "elapsed_s": elapsed,
        "errors": proto.errors,
        "retries": proto.retries,
        "p50_us": lats[len(lats) // 2] * 1e6,
        "p99_us": lats[int(len(lats) * 0.99)] * 1e6,
    }


DNSBLAST = os.path.join(ROOT, "native", "build", "dnsblast")


def _drive_native(port: int, tmpdir: str) -> Dict[str, float]:
    """Drive load with the C++ generator (native/loadgen/dnsblast.cpp).

    On a single-core box the Python client's interpreter cost competes
    with the server for the same CPU; the native client keeps measurement
    overhead negligible so the number reported is server capacity."""
    tmpl_path = os.path.join(tmpdir, "queries.bin")
    with open(tmpl_path, "wb") as f:
        for name, qtype in BENCH_MIX:
            wire = make_query(name, qtype, qid=0).encode()
            f.write(len(wire).to_bytes(2, "big") + wire)
    out = subprocess.run(
        [DNSBLAST, "-p", str(port), "-n", str(N_QUERIES),
         "-w", str(CONCURRENCY), "-t", tmpl_path],
        capture_output=True, text=True, timeout=330, check=True)
    return json.loads(out.stdout)


MBALANCER = os.path.join(ROOT, "native", "build", "mbalancer")


def _bench_topology(tmpdir: str) -> Dict[str, float]:
    """Deployment-shape measurement: mbalancer fronting 2 backends over
    the balancer socket protocol, driven with the same query mix.  Two
    passes; the second (warm balancer cache) is reported."""
    sockdir = os.path.join(tmpdir, "vsock")
    os.mkdir(sockdir)
    fixture = os.path.join(tmpdir, "fixture.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)

    def _reap(proc):
        try:
            proc.terminate()
            proc.wait(timeout=10)
        except Exception:
            try:
                proc.kill()
            except Exception:
                pass

    procs = []   # every child, reaped on any exit path
    try:
        for i in range(2):
            config = os.path.join(tmpdir, f"bconfig{i}.json")
            with open(config, "w") as f:
                json.dump({
                    "dnsDomain": "bench.com", "datacenterName": "dc0",
                    "host": "127.0.0.1",
                    "store": {"backend": "fake", "fixture": fixture},
                    "queryLog": False,
                    "balancerSocket": os.path.join(sockdir, str(i)),
                }, f)
            env = dict(os.environ)
            env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH",
                                                            "")
            p = subprocess.Popen(
                [sys.executable, "-u", "-m", "binder_tpu.main", "-f",
                 config, "-p", "0"],
                cwd=ROOT, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL)
            procs.append(p)
            wait_for_port(p)
        bal = subprocess.Popen(
            [MBALANCER, "-d", sockdir, "-p", "0", "-b", "127.0.0.1",
             "-s", "300"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
        procs.append(bal)
        port = _wait_for_line(bal, rb"PORT (\d+)\n", "mbalancer")
        time.sleep(0.5)   # backend scan + connect
        res = None
        for _ in range(2):   # pass 1 warms the balancer cache
            res = _drive_native(port, tmpdir)
        return res
    finally:
        for p in reversed(procs):   # balancer first, then backends
            _reap(p)


def run_bench() -> Dict[str, object]:
    topo = None
    with tempfile.TemporaryDirectory() as tmpdir:
        proc = start_server(tmpdir)
        try:
            port = wait_for_port(proc)
            if os.access(DNSBLAST, os.X_OK):
                res = _drive_native(port, tmpdir)
            else:
                res = asyncio.run(_drive(port))
        finally:
            proc.terminate()
            proc.wait(timeout=10)
        if os.access(DNSBLAST, os.X_OK) and os.access(MBALANCER, os.X_OK):
            try:
                topo = _bench_topology(tmpdir)
            except Exception:
                topo = None   # topology figure is supplementary

    baseline = None
    if os.path.exists(BASELINE_FILE):
        try:
            with open(BASELINE_FILE) as f:
                baseline = json.load(f).get("qps")
        except (OSError, ValueError):
            baseline = None
    if not baseline:
        # first measured value becomes the local baseline (the reference
        # publishes no numbers — BASELINE.md)
        with open(BASELINE_FILE, "w") as f:
            json.dump({"qps": res["qps"],
                       "note": "first local measurement; reference "
                               "publishes no numbers (BASELINE.md)"}, f)
        baseline = res["qps"]

    out = {
        "metric": "dns_queries_per_sec",
        "value": round(res["qps"], 1),
        "unit": "qps",
        "vs_baseline": round(res["qps"] / baseline, 3),
        "p50_us": round(res["p50_us"], 1),
        "p99_us": round(res["p99_us"], 1),
        "errors": res["errors"],
        "retries": res.get("retries", 0),
        "queries": N_QUERIES,
        "concurrency": CONCURRENCY,
    }
    if topo is not None:
        # supplementary: deployment shape (balancer + 2 backends), warm
        out["topology_qps"] = round(topo["qps"], 1)
        out["topology_p50_us"] = round(topo["p50_us"], 1)
    return out
