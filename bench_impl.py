"""Full-stack DNS benchmark (invoked by bench.py).

Measures the BASELINE.md proxy metric — DNS queries/sec and resolve-latency
percentiles — end-to-end: real UDP datagrams through the transport engine,
resolution engine, and mirror cache (the reference's hot path, SURVEY §3.2),
using the in-memory fake store exactly where the reference would hit its
in-memory ZK mirror.

Query mix mirrors BASELINE.json's proxy configs: single-host A lookups,
round-robin service A lookups, SRV lookups, and PTR lookups.
"""
from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Dict, List

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "bench.com"
N_QUERIES = int(os.environ.get("BENCH_QUERIES", "20000"))
CONCURRENCY = int(os.environ.get("BENCH_CONCURRENCY", "32"))
BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_BASELINE.json")


def build_fixture() -> MirrorCache:
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/bench/web",
                   {"type": "host", "host": {"address": "10.1.0.1"}})
    store.put_json("/com/bench/svc", {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 8080},
    })
    for i in range(8):
        store.put_json(f"/com/bench/svc/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.1.1.{i + 1}"}})
    store.start_session()
    return cache


class BenchClient(asyncio.DatagramProtocol):
    """Windowed UDP load generator: keeps CONCURRENCY queries in flight."""

    def __init__(self, queries: List[bytes], done: asyncio.Future) -> None:
        self.queries = queries
        self.done = done
        self.next_idx = 0
        self.received = 0
        self.latencies: List[float] = []
        self.sent_at: Dict[int, float] = {}
        self.errors = 0

    def connection_made(self, transport) -> None:
        self.transport = transport
        for _ in range(min(CONCURRENCY, len(self.queries))):
            self._send_next()

    def _send_next(self) -> None:
        i = self.next_idx
        if i >= len(self.queries):
            return
        self.next_idx += 1
        self.sent_at[i] = time.perf_counter()
        self.transport.sendto(self.queries[i])

    def datagram_received(self, data, addr) -> None:
        now = time.perf_counter()
        qid = int.from_bytes(data[:2], "big")
        t0 = self.sent_at.pop(qid, None)
        if t0 is not None:
            self.latencies.append(now - t0)
        msg = Message.decode(data)
        if msg.rcode not in (Rcode.NOERROR,):
            self.errors += 1
        self.received += 1
        if self.received >= len(self.queries):
            if not self.done.done():
                self.done.set_result(None)
        else:
            self._send_next()


async def _bench() -> Dict[str, float]:
    cache = build_fixture()
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="dc0", host="127.0.0.1", port=0,
                          collector=MetricsCollector())
    await server.start()

    mix = [
        ("web.bench.com", Type.A),
        ("svc.bench.com", Type.A),
        ("_http._tcp.svc.bench.com", Type.SRV),
        ("1.0.1.10.in-addr.arpa", Type.PTR),
    ]
    queries = [make_query(*mix[i % len(mix)], qid=i % 65536).encode()
               for i in range(N_QUERIES)]

    loop = asyncio.get_running_loop()
    done = loop.create_future()
    t0 = time.perf_counter()
    transport, proto = await loop.create_datagram_endpoint(
        lambda: BenchClient(queries, done),
        remote_addr=("127.0.0.1", server.udp_port))
    await asyncio.wait_for(done, timeout=120)
    elapsed = time.perf_counter() - t0
    transport.close()
    await server.stop()

    lats = sorted(proto.latencies)
    qps = N_QUERIES / elapsed
    return {
        "qps": qps,
        "elapsed_s": elapsed,
        "errors": proto.errors,
        "p50_us": lats[len(lats) // 2] * 1e6,
        "p99_us": lats[int(len(lats) * 0.99)] * 1e6,
    }


def run_bench() -> Dict[str, object]:
    res = asyncio.run(_bench())

    baseline = None
    if os.path.exists(BASELINE_FILE):
        try:
            with open(BASELINE_FILE) as f:
                baseline = json.load(f).get("qps")
        except (OSError, ValueError):
            baseline = None
    if not baseline:
        # first measured value becomes the local baseline (the reference
        # publishes no numbers — BASELINE.md)
        with open(BASELINE_FILE, "w") as f:
            json.dump({"qps": res["qps"],
                       "note": "first local measurement; reference "
                               "publishes no numbers (BASELINE.md)"}, f)
        baseline = res["qps"]

    return {
        "metric": "dns_queries_per_sec",
        "value": round(res["qps"], 1),
        "unit": "qps",
        "vs_baseline": round(res["qps"] / baseline, 3),
        "p50_us": round(res["p50_us"], 1),
        "p99_us": round(res["p99_us"], 1),
        "errors": res["errors"],
        "queries": N_QUERIES,
        "concurrency": CONCURRENCY,
    }
